"""Golden-logit parity for Qwen2-VL vs HF transformers (VERDICT r4 item 4).

Same technique as tests/test_golden_vision.py: a tiny seeded HF
Qwen2VLForConditionalGeneration saved as a real checkpoint, loaded through
``load_vlm`` (2D-rope ViT tower + patch merger + canonical-name LM), and an
image request must reproduce HF's logits end to end. This pins: the Conv3d
-> patchify-matmul conversion, merge-group patch ordering, the tower's 2D
rotary embeddings, the merger MLP, M-RoPE position-id construction
(``mrope_position_ids`` vs HF ``get_rope_index``), and the sectioned 3D
rope application in the LM (``ops/rope.apply_mrope``).

Reference parity target:
`examples/multimodal/components/encode_worker.py:61-179` (Qwen2-VL is the
reference's primary multimodal family).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.loader import load_vlm  # noqa: E402
from dynamo_tpu.models.qwen2_vl import (  # noqa: E402
    encode_qwen2vl,
    mrope_position_ids,
    patchify_frames,
)

IMAGE_TOKEN, VIDEO_TOKEN, VISION_START = 250, 251, 252


def _tiny_qwen2vl():
    from transformers import Qwen2VLConfig, Qwen2VLForConditionalGeneration

    torch.manual_seed(0)
    cfg = Qwen2VLConfig(
        vision_config=dict(
            embed_dim=32, depth=2, num_heads=2, patch_size=4,
            temporal_patch_size=2, spatial_merge_size=2, in_channels=3,
            hidden_size=64, mlp_ratio=2.0,
        ),
        text_config=dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            rope_theta=10000.0, tie_word_embeddings=False,
            rope_scaling={"type": "mrope", "mrope_section": [2, 3, 3]},
        ),
        image_token_id=IMAGE_TOKEN, video_token_id=VIDEO_TOKEN,
        vision_start_token_id=VISION_START,
    )
    return Qwen2VLForConditionalGeneration(cfg).eval().float()


def _patches(seed: int, grid_hw=(8, 8)):
    """Random normalized frames -> (flattened patches, grid) in both our and
    HF's layout (identical by construction — patchify parity is separately
    pinned against HF's processor in test_multimodal_qwen2vl.py)."""
    from dynamo_tpu.models.qwen2_vl import TEST_TINY_QWEN2VL_VISION as VC

    h, w = grid_hw[0] * VC.patch_size, grid_hw[1] * VC.patch_size
    rng = np.random.default_rng(seed)
    frames = rng.standard_normal((VC.temporal_patch_size, 3, h, w)).astype(np.float32) * 0.4
    return patchify_frames(frames, VC)


def test_golden_qwen2vl_tower(tmp_path):
    """Tower + merger in isolation vs HF ``model.visual`` — localizes
    failures to vision vs LM."""
    m = _tiny_qwen2vl()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    _tcfg, vcfg, _lm, vis_params = load_vlm(tmp_path, dtype="float32")
    assert vcfg.embed_dim == 32 and vcfg.spatial_merge_size == 2

    patches, grid = _patches(0)
    with torch.no_grad():
        want = m.model.visual(
            torch.tensor(patches), grid_thw=torch.tensor([list(grid)])
        ).float().numpy()
    got = np.asarray(encode_qwen2vl(vis_params, vcfg, jnp.asarray(patches), grid))
    assert got.shape == want.shape == (grid[0] * grid[1] * grid[2] // 4, 64)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_golden_qwen2vl_image_logits(tmp_path):
    """Full model: image + text prompt -> logits must match HF, prefill AND
    one decode step on the image-conditioned paged cache (M-RoPE deltas)."""
    m = _tiny_qwen2vl()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    tcfg, vcfg, lm_params, vis_params = load_vlm(tmp_path, dtype="float32")
    assert tcfg.image_token_id == IMAGE_TOKEN
    assert tcfg.mrope_section == (2, 3, 3)
    assert tcfg.attention_bias  # Qwen2-VL text uses qkv biases

    patches, grid = _patches(1)
    n_img = grid[0] * grid[1] * grid[2] // 4  # merged tokens
    prompt = [3, 7, VISION_START] + [IMAGE_TOKEN] * n_img + [11, 42, 99, 5]
    t = len(prompt)

    with torch.no_grad():
        hf_logits = m(
            input_ids=torch.tensor([prompt]),
            pixel_values=torch.tensor(patches),
            image_grid_thw=torch.tensor([list(grid)]),
        ).logits[0].float().numpy()

    mm = encode_qwen2vl(vis_params, vcfg, jnp.asarray(patches), grid)
    pos3, delta = mrope_position_ids(
        prompt, [grid], image_token_id=IMAGE_TOKEN, video_token_id=VIDEO_TOKEN,
    )

    page_size = 8
    k_cache, v_cache = llama.init_kv_cache(tcfg, num_pages=16, page_size=page_size)
    n_pages = -(-t // page_size)
    tables = jnp.asarray([list(range(1, 1 + n_pages))], jnp.int32)
    positions = jnp.arange(t, dtype=jnp.int32)[None]
    slots = jnp.take_along_axis(tables, positions // page_size, axis=1) * page_size + positions % page_size
    ours, k_cache, v_cache = llama.forward(
        lm_params, tcfg, jnp.asarray([prompt], jnp.int32), positions,
        k_cache, v_cache, tables, slots, jnp.asarray([t - 1], jnp.int32),
        mm_embeds=mm[None], mrope_positions=jnp.asarray(pos3)[None],
    )
    np.testing.assert_allclose(np.asarray(ours)[0], hf_logits[t - 1], atol=2e-3, rtol=1e-3)

    # Decode step: all three coords sit at (t + delta).
    tok = 42
    pos = jnp.asarray([[t]], jnp.int32)
    pos3_dec = jnp.full((1, 3, 1), t + delta, jnp.int32)
    slot = jnp.take_along_axis(tables, pos // page_size, axis=1) * page_size + pos % page_size
    ours2, _, _ = llama.forward(
        lm_params, tcfg, jnp.asarray([[tok]], jnp.int32), pos,
        k_cache, v_cache, tables, slot, jnp.asarray([0], jnp.int32),
        mrope_positions=pos3_dec,
    )
    with torch.no_grad():
        hf2 = m(
            input_ids=torch.tensor([prompt + [tok]]),
            pixel_values=torch.tensor(patches),
            image_grid_thw=torch.tensor([list(grid)]),
        ).logits[0, -1].float().numpy()
    np.testing.assert_allclose(np.asarray(ours2)[0], hf2, atol=2e-3, rtol=1e-3)


@pytest.mark.e2e
async def test_real_qwen2vl_checkpoint_served_e2e(tmp_path):
    """A real (tiny, seeded) Qwen2-VL checkpoint directory served through the
    full HTTP stack: loader -> native-resolution tower in the encode worker
    -> grid-dependent placeholder expansion -> M-RoPE prefill + decode.
    Pixels must matter."""
    import base64
    import io

    import aiohttp
    from PIL import Image

    from dynamo_tpu.launch import run_local

    m = _tiny_qwen2vl()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    name = tmp_path.name

    def data_url(color, size=(32, 24)):
        img = Image.new("RGB", size, color)
        buf = io.BytesIO()
        img.save(buf, format="PNG")
        return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()

    handles = await run_local(str(tmp_path), port=0, num_pages=128, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        async def ask(color):
            body = {
                "model": name,
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "describe: "},
                    {"type": "image_url", "image_url": {"url": data_url(color)}},
                ]}],
                "max_tokens": 6, "temperature": 0,
            }
            async with aiohttp.ClientSession() as s:
                async with s.post(base + "/v1/chat/completions", json=body) as r:
                    assert r.status == 200, await r.text()
                    return await r.json()

        red = await ask((255, 0, 0))
        blue = await ask((0, 0, 255))
        # 32x24 at patch 4 -> grid (1, 6, 8) -> 12 merged placeholder tokens.
        assert red["usage"]["prompt_tokens"] > 12
        assert red["choices"][0]["message"]["content"] != blue["choices"][0]["message"]["content"]

        from dynamo_tpu.encode import EncodeService
        enc = next(s for s in handles["services"] if isinstance(s, EncodeService))
        assert enc.images_encoded == 2
        assert enc.is_qwen2vl
        # The engine actually built M-RoPE state for the requests.
        eng = next(s for s in handles["services"] if hasattr(s, "core"))
        assert eng.core.runner.cfg.mrope_section == (2, 3, 3)
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


def test_mrope_position_ids_match_hf(tmp_path):
    """``mrope_position_ids`` vs HF ``get_rope_index`` on text+image+text,
    two images, and a trailing-image prompt."""
    m = _tiny_qwen2vl()
    grids = [(1, 8, 8), (1, 4, 8)]
    n1 = 8 * 8 // 4
    n2 = 4 * 8 // 4
    prompts = [
        [1, 2, VISION_START] + [IMAGE_TOKEN] * n1 + [5, 6, 7],
        [VISION_START] + [IMAGE_TOKEN] * n1 + [9, VISION_START] + [IMAGE_TOKEN] * n2 + [4],
        [8, 3, VISION_START] + [IMAGE_TOKEN] * n2,
    ]
    uses = [[grids[0]], grids, [grids[1]]]
    for prompt, gs in zip(prompts, uses):
        # ``gs`` entries are PRE-merge patch grids, HF's image_grid_thw unit.
        want_pos, want_delta = m.model.get_rope_index(
            input_ids=torch.tensor([prompt]),
            image_grid_thw=torch.tensor([list(g) for g in gs]),
        )
        got_pos, got_delta = mrope_position_ids(
            prompt, gs, image_token_id=IMAGE_TOKEN, video_token_id=VIDEO_TOKEN,
        )
        np.testing.assert_array_equal(got_pos, want_pos[:, 0].numpy())
        assert got_delta == int(want_delta[0, 0])
