"""Sampler unit tests: penalties, candidate-window behavior, determinism.

Parity: OpenAI frequency/presence penalty semantics the reference accepts in
its request schema (`lib/llm/src/protocols/openai/*`) and hands to engines.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.sampling import sample_tokens


def _keys(b, seed=0):
    return jax.vmap(jax.random.PRNGKey)(np.arange(seed, seed + b, dtype=np.uint32))


def test_greedy_is_exact_argmax():
    logits = jnp.asarray(np.random.default_rng(0).standard_normal((4, 1000)), jnp.float32)
    toks = sample_tokens(logits, _keys(4), jnp.zeros(4), jnp.zeros(4, jnp.int32), jnp.ones(4))
    np.testing.assert_array_equal(np.asarray(toks), np.argmax(np.asarray(logits), -1))


def test_frequency_penalty_demotes_repeated_token():
    """A token that dominates the logits but already appeared H times loses
    to the runner-up once freq_penalty * H exceeds the logit gap."""
    b, v = 2, 512
    logits = np.zeros((b, v), np.float32)
    logits[:, 7] = 5.0  # dominant
    logits[:, 3] = 4.5  # runner-up
    history = np.full((b, 8), -1, np.int32)
    history[0, :4] = 7  # row 0: token 7 already emitted 4 times
    # row 1: clean history
    freq = np.asarray([0.5, 0.5], np.float32)  # 0.5 * 4 = 2.0 > gap 0.5
    pres = np.zeros(b, np.float32)
    toks = sample_tokens(
        jnp.asarray(logits), _keys(b), jnp.zeros(b), jnp.zeros(b, jnp.int32), jnp.ones(b),
        history=jnp.asarray(history), frequency_penalty=jnp.asarray(freq),
        presence_penalty=jnp.asarray(pres),
    )
    assert int(toks[0]) == 3  # demoted
    assert int(toks[1]) == 7  # untouched


def test_presence_penalty_is_count_independent():
    """Presence penalty applies once regardless of occurrence count."""
    b, v = 2, 512
    logits = np.zeros((b, v), np.float32)
    logits[:, 7] = 5.0
    logits[:, 3] = 4.8
    history = np.full((b, 8), -1, np.int32)
    history[0, 0] = 7   # once
    history[1, :6] = 7  # six times
    pres = np.full(b, 0.3, np.float32)  # 0.3 > gap 0.2: demoted either way
    freq = np.zeros(b, np.float32)
    toks = sample_tokens(
        jnp.asarray(logits), _keys(b), jnp.zeros(b), jnp.zeros(b, jnp.int32), jnp.ones(b),
        history=jnp.asarray(history), frequency_penalty=jnp.asarray(freq),
        presence_penalty=jnp.asarray(pres),
    )
    assert int(toks[0]) == 3 and int(toks[1]) == 3


def test_zero_penalties_match_unpenalized_path():
    rng = np.random.default_rng(1)
    b, v = 4, 2048
    logits = jnp.asarray(rng.standard_normal((b, v)), jnp.float32)
    history = jnp.asarray(rng.integers(0, v, (b, 16)), jnp.int32)
    kw = dict(temperature=jnp.full(b, 0.8), top_k=jnp.full(b, 40, jnp.int32), top_p=jnp.full(b, 0.95))
    base = sample_tokens(logits, _keys(b, 9), kw["temperature"], kw["top_k"], kw["top_p"])
    pen = sample_tokens(
        logits, _keys(b, 9), kw["temperature"], kw["top_k"], kw["top_p"],
        history=history, frequency_penalty=jnp.zeros(b), presence_penalty=jnp.zeros(b),
    )
    np.testing.assert_array_equal(np.asarray(base), np.asarray(pen))


def test_engine_applies_penalties_end_to_end():
    """A strong frequency penalty must change what the engine generates vs
    the same seeded request without it (the API contract: the parameter is
    applied, not silently dropped)."""
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)

    def run(freq_pen):
        runner = ModelRunner(cfg, params, num_pages=64, page_size=4, max_batch_size=4)
        core = EngineCore(runner, EngineConfig(num_pages=64, page_size=4, max_batch_size=4,
                                               decode_steps=4))
        req = PreprocessedRequest(
            token_ids=[5, 6, 7, 8, 9, 10, 11, 12],
            sampling=SamplingOptions(temperature=0.0, frequency_penalty=freq_pen),
            stop=StopConditions(max_tokens=24, ignore_eos=True),
        )
        seq = core.add_request(req)
        while not seq.is_finished:
            core.step()
        return seq.tokens[seq.num_prompt:]

    plain = run(0.0)
    penalized = run(2.0)
    assert len(plain) == len(penalized) == 24
    # Greedy tiny-model output loops hard; the penalty must break the loop.
    assert plain != penalized
    top_plain = max(plain.count(t) for t in set(plain))
    top_pen = max(penalized.count(t) for t in set(penalized))
    assert top_pen < top_plain, (top_plain, top_pen)


def test_penalty_respects_topk_ordering():
    """Regression: penalties must re-sort the candidate window, or top_k=1
    keeps sampling the demoted pre-penalty winner."""
    b, v = 1, 512
    logits = np.zeros((b, v), np.float32)
    logits[:, 7] = 5.0
    logits[:, 3] = 4.5
    history = np.full((b, 8), -1, np.int32)
    history[0, :4] = 7
    toks = sample_tokens(
        jnp.asarray(logits), _keys(b), jnp.ones(b), jnp.full(b, 1, jnp.int32), jnp.ones(b),
        history=jnp.asarray(history), frequency_penalty=jnp.full(b, 1.0),
        presence_penalty=jnp.zeros(b),
    )
    assert int(toks[0]) == 3  # top_k=1 must pick the *post-penalty* max


def test_penalty_respects_topp_mass():
    """With top_p ~0, only the post-penalty argmax may be sampled."""
    b, v = 1, 512
    logits = np.zeros((b, v), np.float32)
    logits[:, 7] = 8.0
    logits[:, 3] = 7.0
    history = np.full((b, 4), -1, np.int32)
    history[0, :2] = 7
    toks = sample_tokens(
        jnp.asarray(logits), _keys(b), jnp.ones(b), jnp.zeros(b, jnp.int32),
        jnp.full(b, 0.01),
        history=jnp.asarray(history), frequency_penalty=jnp.full(b, 2.0),
        presence_penalty=jnp.zeros(b),
    )
    assert int(toks[0]) == 3
