"""Disaggregated prefill/decode tests.

Covers the queue (claim/ack/reclaim), the disagg decision, KV transfer
injection, and the full topology E2E: a long prompt is prefilled by the
prefill fleet, its KV injected into the decode worker's cache, and decode
produces token-exact output with most of the prompt cached remotely.
"""

import asyncio

import aiohttp
import pytest

from dynamo_tpu.disagg.queue import DistributedQueue
from dynamo_tpu.disagg.router import DisaggConfig, DisaggRouter, config_key
from dynamo_tpu.launch import run_local
from dynamo_tpu.runtime.component import DistributedRuntime


# -- queue -------------------------------------------------------------------


async def test_queue_put_claim_ack():
    rt = DistributedRuntime.detached()
    try:
        q = DistributedQueue(rt, "test")
        await q.put({"x": 1})
        await q.put({"x": 2})
        assert await q.depth() == 2
        key1, item1 = await q.claim(timeout=2)
        assert item1["x"] == 1  # FIFO by key order
        assert await q.depth() == 1
        # Same consumer can't double-claim; a second claim gets task 2.
        key2, item2 = await q.claim(timeout=2)
        assert item2["x"] == 2
        await q.delete(key1)
        await q.delete(key2)
        assert await q.depth() == 0
        assert await q.claim(timeout=0.2) is None
    finally:
        await rt.close()


async def test_queue_reclaim_after_claimant_death():
    rt = DistributedRuntime.detached()
    try:
        producer = DistributedQueue(rt, "test")
        await producer.put({"job": "a"})
        # Claimant is a different runtime sharing the store, with a short lease.
        claimant = DistributedRuntime(rt.store, rt.transport, lease_ttl=0.3)
        cq = DistributedQueue(claimant, "test")
        key, _ = await cq.claim(timeout=2)
        assert await producer.claim(timeout=0.2) is None  # claimed: unavailable
        # Claimant "dies": stop keepalive, let the lease expire.
        claimant._keepalive_task.cancel()
        await asyncio.sleep(0.8)
        reclaimed = await producer.claim(timeout=3)
        assert reclaimed is not None and reclaimed[1]["job"] == "a"
    finally:
        await rt.close()


# -- decision ----------------------------------------------------------------


def test_disagg_decision_thresholds():
    r = DisaggRouter(DisaggConfig(max_local_prefill_length=100, max_prefill_queue_size=4,
                                  min_remote_prefill_blocks=2), page_size=16)
    assert not r.prefill_remote(50)  # short: local
    assert r.prefill_remote(200)  # long: remote
    assert not r.prefill_remote(200, queue_depth=5)  # queue too deep: local
    assert not r.prefill_remote(31)  # < 2 blocks: local regardless
    r.config.enabled = False
    assert not r.prefill_remote(5000)


async def test_disagg_config_hot_reload():
    rt = DistributedRuntime.detached()
    try:
        r = await DisaggRouter(DisaggConfig(max_local_prefill_length=100)).watch(rt, "dynamo")
        assert r.prefill_remote(200)
        await rt.store.put(config_key("dynamo"), DisaggConfig(max_local_prefill_length=1000).to_json())
        for _ in range(50):
            if r.config.max_local_prefill_length == 1000:
                break
            await asyncio.sleep(0.02)
        assert not r.prefill_remote(200)
        await r.close()
    finally:
        await rt.close()


# -- full topology E2E -------------------------------------------------------


@pytest.mark.e2e
async def test_disagg_e2e_remote_prefill():
    disagg = DisaggConfig(max_local_prefill_length=24, min_remote_prefill_blocks=1)
    handles = await run_local(
        "test-tiny", port=0, num_workers=1, num_prefill_workers=1,
        disagg=disagg, num_pages=64, max_batch_size=8,
    )
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        decode_svc = handles["services"][0]
        long_prompt = "r" * 48  # 48 tokens > threshold 24 -> remote prefill
        short_prompt = "s" * 8

        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "prompt": long_prompt, "max_tokens": 4, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
            # Remote prefill happened: the decode engine saw >= 2 pages cached
            # at admission (injected by the prefill worker), tail computed locally.
            assert out["usage"]["prompt_tokens_details"]["cached_tokens"] >= 32

            # Output must equal a pure-local run of the same prompt.
            body_local = {"model": "test-tiny", "prompt": short_prompt, "max_tokens": 4, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body_local) as r:
                assert r.status == 200  # short prompt: local path still works

        # Counters: one remote, one local.
        prefill_svc = handles["services"][1]
        prefill_worker = prefill_svc.aux[-1]
        assert prefill_worker.completed == 1
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


@pytest.mark.e2e
async def test_disagg_output_matches_aggregated():
    """Same prompt through disagg and plain topologies -> identical tokens."""
    prompt = "t" * 40

    async def run_topology(**kw):
        handles = await run_local("test-tiny", port=0, num_pages=64, max_batch_size=8, **kw)
        try:
            async with aiohttp.ClientSession() as s:
                body = {"model": "test-tiny", "prompt": prompt, "max_tokens": 6, "temperature": 0}
                async with s.post(f"http://127.0.0.1:{handles['port']}/v1/completions", json=body) as r:
                    assert r.status == 200, await r.text()
                    return (await r.json())["choices"][0]["text"]
        finally:
            await handles["http"].stop()
            await handles["watcher"].close()
            for svc in handles["services"]:
                await svc.close()
            await handles["runtime"].close()

    plain = await run_topology(num_workers=1)
    disagg = await run_topology(
        num_workers=1, num_prefill_workers=1,
        disagg=DisaggConfig(max_local_prefill_length=16, min_remote_prefill_blocks=1),
    )
    assert disagg == plain


# -- leader/worker barrier ---------------------------------------------------


async def test_leader_worker_barrier():
    from dynamo_tpu.runtime.barrier import BarrierTimeout, leader_barrier, worker_barrier

    rt = DistributedRuntime.detached()
    try:
        data = {"coordinator": "10.0.0.1:8476", "mesh": [2, 4]}

        async def worker(i):
            return await worker_barrier(rt, "boot", f"w{i}", timeout=5)

        results = await asyncio.gather(
            leader_barrier(rt, "boot", data, num_workers=3, timeout=5),
            worker(0), worker(1), worker(2),
        )
        assert results[1] == results[2] == results[3] == data

        with pytest.raises(BarrierTimeout):
            await leader_barrier(rt, "boot2", {}, num_workers=1, timeout=0.2)
    finally:
        await rt.close()
