"""Disaggregated prefill/decode tests.

Covers the queue (claim/ack/reclaim), the disagg decision, KV transfer
injection, and the full topology E2E: a long prompt is prefilled by the
prefill fleet, its KV injected into the decode worker's cache, and decode
produces token-exact output with most of the prompt cached remotely.
"""

import asyncio

import aiohttp
import pytest

from dynamo_tpu.disagg.queue import DistributedQueue
from dynamo_tpu.disagg.router import DisaggConfig, DisaggRouter, config_key
from dynamo_tpu.launch import run_local
from dynamo_tpu.runtime.component import DistributedRuntime


# -- queue -------------------------------------------------------------------


async def test_queue_put_claim_ack():
    rt = DistributedRuntime.detached()
    try:
        q = DistributedQueue(rt, "test")
        await q.put({"x": 1})
        await q.put({"x": 2})
        assert await q.depth() == 2
        key1, item1 = await q.claim(timeout=2)
        assert item1["x"] == 1  # FIFO by key order
        assert await q.depth() == 1
        # Same consumer can't double-claim; a second claim gets task 2.
        key2, item2 = await q.claim(timeout=2)
        assert item2["x"] == 2
        await q.delete(key1)
        await q.delete(key2)
        assert await q.depth() == 0
        assert await q.claim(timeout=0.2) is None
    finally:
        await rt.close()


async def test_queue_reclaim_after_claimant_death():
    rt = DistributedRuntime.detached()
    try:
        producer = DistributedQueue(rt, "test")
        await producer.put({"job": "a"})
        # Claimant is a different runtime sharing the store, with a short lease.
        claimant = DistributedRuntime(rt.store, rt.transport, lease_ttl=0.3)
        cq = DistributedQueue(claimant, "test")
        key, _ = await cq.claim(timeout=2)
        assert await producer.claim(timeout=0.2) is None  # claimed: unavailable
        # Claimant "dies": stop keepalive, let the lease expire.
        claimant._keepalive_task.cancel()
        await asyncio.sleep(0.8)
        reclaimed = await producer.claim(timeout=3)
        assert reclaimed is not None and reclaimed[1]["job"] == "a"
    finally:
        await rt.close()


# -- decision ----------------------------------------------------------------


def test_disagg_decision_thresholds():
    r = DisaggRouter(DisaggConfig(max_local_prefill_length=100, max_prefill_queue_size=4,
                                  min_remote_prefill_blocks=2), page_size=16)
    assert not r.prefill_remote(50)  # short: local
    assert r.prefill_remote(200)  # long: remote
    assert not r.prefill_remote(200, queue_depth=5)  # queue too deep: local
    assert not r.prefill_remote(31)  # < 2 blocks: local regardless
    r.config.enabled = False
    assert not r.prefill_remote(5000)


async def test_disagg_config_hot_reload():
    rt = DistributedRuntime.detached()
    try:
        r = await DisaggRouter(DisaggConfig(max_local_prefill_length=100)).watch(rt, "dynamo")
        assert r.prefill_remote(200)
        await rt.store.put(config_key("dynamo"), DisaggConfig(max_local_prefill_length=1000).to_json())
        for _ in range(50):
            if r.config.max_local_prefill_length == 1000:
                break
            await asyncio.sleep(0.02)
        assert not r.prefill_remote(200)
        await r.close()
    finally:
        await rt.close()


# -- full topology E2E -------------------------------------------------------


@pytest.mark.e2e
async def test_disagg_e2e_remote_prefill():
    disagg = DisaggConfig(max_local_prefill_length=24, min_remote_prefill_blocks=1)
    handles = await run_local(
        "test-tiny", port=0, num_workers=1, num_prefill_workers=1,
        disagg=disagg, num_pages=64, max_batch_size=8,
    )
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        decode_svc = handles["services"][0]
        long_prompt = "r" * 48  # 48 tokens > threshold 24 -> remote prefill
        short_prompt = "s" * 8

        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "prompt": long_prompt, "max_tokens": 4, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
            # Remote prefill happened: the decode engine saw >= 2 pages cached
            # at admission (injected by the prefill worker), tail computed locally.
            assert out["usage"]["prompt_tokens_details"]["cached_tokens"] >= 32

            # Output must equal a pure-local run of the same prompt.
            body_local = {"model": "test-tiny", "prompt": short_prompt, "max_tokens": 4, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body_local) as r:
                assert r.status == 200  # short prompt: local path still works

        # Counters: one remote, one local.
        prefill_svc = handles["services"][1]
        prefill_worker = prefill_svc.aux[-1]
        assert prefill_worker.completed == 1

        # Same-process topology: the KV moved over the device path (no TCP
        # host bounce) and the service measured its bandwidth.
        from dynamo_tpu.disagg.device_transfer import REGISTRY

        transfer_svc = next(iter(REGISTRY._services.values()))
        st = transfer_svc.stats()
        assert st["device_path_blocks"] >= 2, st
        assert st["gbytes_per_sec"] > 0, st
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


@pytest.mark.e2e
async def test_disagg_output_matches_aggregated():
    """Same prompt through disagg and plain topologies -> identical tokens."""
    prompt = "t" * 40

    async def run_topology(**kw):
        handles = await run_local("test-tiny", port=0, num_pages=64, max_batch_size=8, **kw)
        try:
            async with aiohttp.ClientSession() as s:
                body = {"model": "test-tiny", "prompt": prompt, "max_tokens": 6, "temperature": 0}
                async with s.post(f"http://127.0.0.1:{handles['port']}/v1/completions", json=body) as r:
                    assert r.status == 200, await r.text()
                    return (await r.json())["choices"][0]["text"]
        finally:
            await handles["http"].stop()
            await handles["watcher"].close()
            for svc in handles["services"]:
                await svc.close()
            await handles["runtime"].close()

    plain = await run_topology(num_workers=1)
    disagg = await run_topology(
        num_workers=1, num_prefill_workers=1,
        disagg=DisaggConfig(max_local_prefill_length=16, min_remote_prefill_blocks=1),
    )
    assert disagg == plain


# -- leader/worker barrier ---------------------------------------------------


async def test_leader_worker_barrier():
    from dynamo_tpu.runtime.barrier import BarrierTimeout, leader_barrier, worker_barrier

    rt = DistributedRuntime.detached()
    try:
        data = {"coordinator": "10.0.0.1:8476", "mesh": [2, 4]}

        async def worker(i):
            return await worker_barrier(rt, "boot", f"w{i}", timeout=5)

        results = await asyncio.gather(
            leader_barrier(rt, "boot", data, num_workers=3, timeout=5),
            worker(0), worker(1), worker(2),
        )
        assert results[1] == results[2] == results[3] == data

        with pytest.raises(BarrierTimeout):
            await leader_barrier(rt, "boot2", {}, num_workers=1, timeout=0.2)
    finally:
        await rt.close()


def test_device_kv_transfer_pages_and_bandwidth():
    """Device-path transfer: pages land bit-identical in the peer cache and
    the engine reports a measured bandwidth."""
    import numpy as np

    from dynamo_tpu.disagg.device_transfer import DeviceKvTransfer
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    src = ModelRunner(cfg, params, num_pages=16, page_size=4, max_batch_size=4)
    dst = ModelRunner(cfg, params, num_pages=16, page_size=4, max_batch_size=4)

    rng = np.random.default_rng(0)
    payloads = {}
    for pid in (3, 5, 9):
        k = rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32)
        v = rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32)
        src.write_page(pid, k, v)
        payloads[pid] = (k, v)

    xfer = DeviceKvTransfer()
    stats = xfer.transfer(src, [3, 5, 9], dst, [2, 7, 11])
    for src_pid, dst_pid in [(3, 2), (5, 7), (9, 11)]:
        k_got, v_got = dst.read_page(dst_pid)
        np.testing.assert_array_equal(k_got, payloads[src_pid][0])
        np.testing.assert_array_equal(v_got, payloads[src_pid][1])
    assert stats.pages == 3
    assert stats.bytes > 0 and stats.gbytes_per_sec > 0


def test_write_pages_batched_matches_per_page():
    import numpy as np

    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    r = ModelRunner(cfg, params, num_pages=16, page_size=4, max_batch_size=4)
    rng = np.random.default_rng(1)
    pids = [1, 4, 6]  # non-pow2 count exercises padding -> null page
    ks = [rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32) for _ in pids]
    vs = [rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32) for _ in pids]
    r.write_pages(pids, ks, vs)
    for i, pid in enumerate(pids):
        k_got, v_got = r.read_page(pid)
        np.testing.assert_array_equal(k_got, ks[i])
        np.testing.assert_array_equal(v_got, vs[i])


async def test_inject_from_failure_releases_staged_pages(monkeypatch):
    """A device-transfer failure must not strand the staged destination
    pages: they are released back to the free pool and the error propagates
    (the prefill worker then falls back to TCP)."""
    from types import SimpleNamespace

    from dynamo_tpu.disagg import device_transfer
    from dynamo_tpu.disagg.transfer import KvTransferService
    from dynamo_tpu.engine.allocator import PageAllocator
    from dynamo_tpu.tokens import compute_block_hashes

    hashes = compute_block_hashes(list(range(8)), 4, salt=0)
    src_alloc = PageAllocator(16, 4)
    pids = src_alloc.allocate(2)
    src_alloc.commit(pids[0], hashes[0], None)
    src_alloc.commit(pids[1], hashes[1], hashes[0])
    src_alloc.release(pids)

    dst_alloc = PageAllocator(16, 4)
    svc = KvTransferService(SimpleNamespace(allocator=dst_alloc, runner=None))

    def boom(self, *a, **k):
        raise RuntimeError("ici down")

    monkeypatch.setattr(device_transfer.DeviceKvTransfer, "transfer", boom)
    free_before = dst_alloc.num_free()
    with pytest.raises(RuntimeError, match="ici down"):
        await svc.inject_from(SimpleNamespace(allocator=src_alloc, runner=None), hashes[:2])
    assert dst_alloc.num_free() == free_before  # staged pages returned
    # Source refcounts dropped too: the pages are still matchable.
    again = src_alloc.match_prefix(hashes[:2])
    assert len(again) == 2
    src_alloc.release(again)


def test_device_kv_transfer_between_sharded_meshes():
    """Device-path transfer between two runners whose caches are sharded
    over different device subsets: shards land on the destination's devices
    (resharding device_put), and the pages read back bit-identical."""
    import jax
    import numpy as np

    from dynamo_tpu.disagg.device_transfer import DeviceKvTransfer, cache_compatible
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    src_mesh = make_mesh(MeshPlan(dp=1, tp=2), devs[4:6])  # prefill pool
    dst_mesh = make_mesh(MeshPlan(dp=1, tp=2), devs[0:2])  # decode pool
    src = ModelRunner(cfg, params, num_pages=16, page_size=4, max_batch_size=4, mesh=src_mesh)
    dst = ModelRunner(cfg, params, num_pages=16, page_size=4, max_batch_size=4, mesh=dst_mesh)
    assert cache_compatible(src, dst)

    rng = np.random.default_rng(2)
    payloads = {}
    for pid in (2, 6, 7):
        k = rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32)
        v = rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32)
        src.write_page(pid, k, v)
        payloads[pid] = (k, v)

    stats = DeviceKvTransfer().transfer(src, [2, 6, 7], dst, [3, 5, 9])
    assert stats.pages == 3
    # Destination cache still sharded over its own devices.
    assert {d.id for d in dst.k_cache.devices()} == {d.id for d in devs[0:2]}
    for src_pid, dst_pid in [(2, 3), (6, 5), (7, 9)]:
        k_got, v_got = dst.read_page(dst_pid)
        np.testing.assert_array_equal(k_got, payloads[src_pid][0])
        np.testing.assert_array_equal(v_got, payloads[src_pid][1])


def test_kv_injection_pins_cache_hits_under_pressure():
    """Cached chain heads must survive the allocations made later in the
    same injection pass (eviction there would orphan the whole chain)."""
    from types import SimpleNamespace

    from dynamo_tpu.disagg.transfer import KvTransferService, pack_block
    from dynamo_tpu.engine.allocator import PageAllocator
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.tokens import compute_block_hashes
    import numpy as np

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    runner = ModelRunner(cfg, params, num_pages=3, page_size=4, max_batch_size=2)
    # 2 usable pages: h0 cached + 1 free. Injecting [h0, h1, h2] allocates
    # until the pool is exhausted — without pinning, the second allocate
    # would evict h0's page (the chain head) to satisfy h2.
    alloc = PageAllocator(3, 4)
    hashes = compute_block_hashes(list(range(12)), 4, salt=0)
    [p0] = alloc.allocate(1)
    alloc.commit(p0, hashes[0], None)
    alloc.release([p0])

    zeros = np.zeros((cfg.num_layers, 4, cfg.kv_dim), np.float32)
    blocks = [
        pack_block(hashes[0], None, [], zeros, zeros),
        pack_block(hashes[1], hashes[0], [], zeros, zeros),
        pack_block(hashes[2], hashes[1], [], zeros, zeros),
    ]
    svc = KvTransferService(SimpleNamespace(allocator=alloc, runner=runner))

    async def run():
        async for out in svc.generate({"request_id": "r", "blocks": blocks}, Context()):
            return out

    out = asyncio.run(run())
    # h2 was dropped (pool exhausted) — but the chain head survived, so the
    # injected prefix [h0, h1] is intact and matchable.
    assert out["injected"] == 2
    matched = alloc.match_prefix(hashes[:3])
    assert len(matched) == 2
    alloc.release(matched)


def test_runner_cache_io_is_thread_safe():
    """Concurrent cache writes from multiple threads (engine step vs KV
    transfer ingestion) must serialize on the runner's io_lock — without it,
    both threads donate the same buffer and JAX raises 'array deleted'."""
    import threading

    import numpy as np

    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    r = ModelRunner(cfg, params, num_pages=32, page_size=4, max_batch_size=4)
    k = np.ones((cfg.num_layers, 4, cfg.kv_dim), np.float32)
    v = np.ones((cfg.num_layers, 4, cfg.kv_dim), np.float32)
    r.write_pages([1], [k], [v])  # compile outside the race window
    errs: list[Exception] = []

    def hammer(tid: int) -> None:
        try:
            for i in range(40):
                pid = 1 + (tid * 7 + i) % 30
                r.write_pages([pid], [k * tid], [v * i])
                r.read_page(pid)
        except Exception as e:  # pragma: no cover - only on regression
            errs.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


def test_device_transfer_chunks_release_locks_between_chunks():
    """A large migration must not hold the runners' io_locks end to end:
    chunked transfer releases them between chunks so a concurrent decode
    step can interleave (VERDICT r3 weak #3)."""
    import threading
    import time as _time

    import numpy as np

    from dynamo_tpu.disagg.device_transfer import DeviceKvTransfer
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    src = ModelRunner(cfg, params, num_pages=300, page_size=4, max_batch_size=4)
    dst = ModelRunner(cfg, params, num_pages=300, page_size=4, max_batch_size=4)

    rng = np.random.default_rng(1)
    n_pages = 256
    src_pages = list(range(1, 1 + n_pages))
    dst_pages = list(range(1, 1 + n_pages))
    for pid in src_pages[:4]:  # content spot-check set
        k = rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32)
        v = rng.standard_normal((cfg.num_layers, 4, cfg.kv_dim)).astype(np.float32)
        src.write_page(pid, k, v)

    # Make each chunk's scatter visibly slow so the window between chunks
    # is measurable.
    real_write_pages = dst.write_pages

    def slow_write_pages(*a, **kw):
        _time.sleep(0.05)
        return real_write_pages(*a, **kw)

    dst.write_pages = slow_write_pages

    xfer = DeviceKvTransfer()
    done = threading.Event()
    err: list[BaseException] = []

    def run():
        try:
            xfer.transfer(src, src_pages, dst, dst_pages, chunk_pages=32)
        except BaseException as e:  # pragma: no cover
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=run)
    t.start()
    # A "decode step" repeatedly needs dst's io_lock while the migration
    # runs; with per-chunk locking it must get in at least twice.
    acquisitions = 0
    while not done.is_set():
        if dst.io_lock.acquire(timeout=0.01):
            try:
                if not done.is_set():
                    acquisitions += 1
            finally:
                dst.io_lock.release()
        _time.sleep(0.005)
    t.join()
    assert not err, err
    assert acquisitions >= 2, (
        f"io_lock only obtainable {acquisitions}x during a 256-page "
        f"migration — transfer holds the lock end-to-end"
    )
    assert xfer.stats.pages == n_pages
    k_got, v_got = dst.read_page(dst_pages[0])
    k_want, _ = src.read_page(src_pages[0])
    np.testing.assert_array_equal(k_got, k_want)
