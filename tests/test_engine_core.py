"""End-to-end engine core tests on the tiny model (CPU, 8 virtual devices).

Covers: greedy generation determinism vs a naive full-context reference,
prefix-cache reuse across requests, continuous batching of staggered arrivals,
preemption under page pressure, stop conditions, and KV event emission.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context

CFG = PRESETS["test-tiny"]
PARAMS = llama.init_params(CFG, 0)
PAGE = 4


def make_core(num_pages=64, max_batch=8, on_kv_event=None, **cfg_kw):
    config = EngineConfig(
        num_pages=num_pages, page_size=PAGE, max_batch_size=max_batch,
        max_prefill_tokens=256, max_seq_len=128, **cfg_kw,
    )
    runner = ModelRunner(
        CFG, PARAMS, num_pages=num_pages, page_size=PAGE,
        max_batch_size=max_batch, prefill_bucket=16, attn_impl="reference",
    )
    return EngineCore(runner, config, on_kv_event=on_kv_event)


def run_to_completion(core, max_steps=200, outputs=None):
    outputs = outputs if outputs is not None else {}
    for _ in range(max_steps):
        if not core.has_work:
            break
        for seq, out in core.step():
            outputs.setdefault(seq.seq_id, []).extend(out.token_ids)
            if out.finish_reason is not None:
                outputs.setdefault("finish", {})[seq.seq_id] = out.finish_reason
    return outputs


def greedy_reference(prompt, n_gen):
    """Naive full-recompute greedy decoding — ground truth for the engine."""
    tokens = list(prompt)
    num_pages = 64
    for _ in range(n_gen):
        t = len(tokens)
        pages = list(range(1, (t + PAGE - 1) // PAGE + 1))
        bt = np.zeros((1, len(pages)), np.int32)
        bt[0] = pages
        pos = np.arange(t, dtype=np.int32)[None]
        slots = np.asarray([[pages[i // PAGE] * PAGE + i % PAGE for i in range(t)]], np.int32)
        kc, vc = llama.init_kv_cache(CFG, num_pages, PAGE)
        logits, _, _ = llama.forward(
            PARAMS, CFG, jnp.asarray([tokens], jnp.int32), jnp.asarray(pos), kc, vc,
            jnp.asarray(bt), jnp.asarray(slots), jnp.asarray([t - 1], jnp.int32),
            attn_impl="reference",
        )
        tokens.append(int(jnp.argmax(logits[0])))
    return tokens[len(prompt):]


def greedy_request(prompt, max_tokens=8, **kw):
    return PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, **kw),
    )


def test_greedy_matches_full_recompute():
    core = make_core()
    prompt = [5, 6, 7, 8, 9, 10, 11]
    core.add_request(greedy_request(prompt, max_tokens=6))
    outputs = run_to_completion(core)
    assert outputs[0] == greedy_reference(prompt, 6)


def test_batched_staggered_arrivals():
    core = make_core()
    p1, p2 = [1, 2, 3, 4, 5], [9, 8, 7]
    core.add_request(greedy_request(p1, max_tokens=5))
    first = {s.seq_id: out.token_ids for s, out in core.step()}  # prefill 1
    core.add_request(greedy_request(p2, max_tokens=5))  # arrives mid-flight
    outputs = run_to_completion(core)
    assert first[0] + outputs[0] == greedy_reference(p1, 5)
    assert outputs[1] == greedy_reference(p2, 5)


def test_prefix_cache_reuse_across_requests():
    core = make_core()
    prompt = list(range(1, 13))  # 12 tokens = 3 full pages
    core.add_request(greedy_request(prompt, max_tokens=2))
    run_to_completion(core)
    seq = core.add_request(greedy_request(prompt, max_tokens=2))
    out2 = run_to_completion(core)
    # Second request must have matched cached prefix pages (2 full pages:
    # the 3rd is capped so the last prompt token's logits are computed).
    assert seq.num_cached_at_start == 8
    assert out2[seq.seq_id] == greedy_reference(prompt, 2)
    assert core.allocator.stats().hits >= 2


def test_stop_token_id():
    core = make_core()
    prompt = [5, 6, 7]
    ref = greedy_reference(prompt, 8)
    stop_at = ref[2]
    req = greedy_request(prompt, max_tokens=8, stop_token_ids=[stop_at])
    core.add_request(req)
    outputs = run_to_completion(core)
    # Ends at the first occurrence of the stop token (inclusive).
    assert outputs[0] == ref[: ref.index(stop_at) + 1]
    assert outputs["finish"][0] == FinishReason.STOP


def test_eos_and_ignore_eos():
    prompt = [5, 6, 7]
    ref = greedy_reference(prompt, 6)
    eos = ref[1]
    core = make_core(eos_token_ids=(eos,))
    core.add_request(greedy_request(prompt, max_tokens=6))
    outputs = run_to_completion(core)
    assert outputs["finish"][0] == FinishReason.STOP
    assert outputs[0] == ref[: ref.index(eos) + 1]

    core2 = make_core(eos_token_ids=(eos,))
    req = greedy_request(prompt, max_tokens=6, ignore_eos=True)
    core2.add_request(req)
    outputs2 = run_to_completion(core2)
    assert outputs2[0] == ref
    assert outputs2["finish"][0] == FinishReason.LENGTH


def test_preemption_under_page_pressure():
    # 7 usable pages; final footprints are 4+4 pages, so decode MUST preempt
    # one sequence and later resume it (recompute + continue) correctly.
    core = make_core(num_pages=8, max_batch=2, enable_prefix_caching=False)
    p1, p2 = [1, 2, 3, 4, 5, 6], [11, 12, 13, 14]
    core.add_request(greedy_request(p1, max_tokens=10))
    core.add_request(greedy_request(p2, max_tokens=10))
    outputs = run_to_completion(core, max_steps=400)
    assert core.num_preemptions > 0, "test must exercise the preemption path"
    assert outputs[0] == greedy_reference(p1, 10)
    assert outputs[1] == greedy_reference(p2, 10)


def test_decode_batch_with_early_finisher():
    # Three running seqs where seq0 finishes first: remaining rows must stay
    # correctly paired with their sequences (regression: mid-loop removal).
    core = make_core()
    prompts = [[1, 2], [3, 4, 5], [9, 8, 7, 6]]
    maxes = [2, 6, 6]
    for p, m in zip(prompts, maxes):
        core.add_request(greedy_request(p, max_tokens=m))
    outputs = run_to_completion(core)
    for i, (p, m) in enumerate(zip(prompts, maxes)):
        assert outputs[i] == greedy_reference(p, m), f"seq {i}"


def test_cancellation_mid_stream():
    core = make_core()
    ctx = Context()
    core.add_request(greedy_request([1, 2, 3], max_tokens=50), ctx)
    core.step()
    core.step()
    ctx.stop_generating()
    outputs = run_to_completion(core, max_steps=10)
    assert outputs["finish"][0] == FinishReason.CANCELLED
    assert not core.has_work


def test_kv_events_stored_then_removed():
    events = []
    core = make_core(num_pages=16, on_kv_event=events.append)
    prompt = list(range(1, 10))  # 9 tokens -> 2 full pages
    core.add_request(greedy_request(prompt, max_tokens=4))
    run_to_completion(core)
    stored = [s.block_hash for e in events for s in e.stored]
    # Prompt pages 1-2 plus pages filled during decode commit as they complete.
    assert len(stored) >= 2
    # Chained parents: first block has no parent, second's parent is first.
    all_stored = [s for e in events for s in e.stored]
    assert all_stored[0].parent_hash is None
    assert all_stored[1].parent_hash == all_stored[0].block_hash


def test_sampling_seed_determinism():
    def run():
        core = make_core()
        req = PreprocessedRequest(
            token_ids=[3, 1, 4, 1, 5],
            sampling=SamplingOptions(temperature=0.9, top_k=40, top_p=0.95, seed=1234),
            stop=StopConditions(max_tokens=8),
        )
        core.add_request(req)
        return run_to_completion(core)[0]

    a, b = run(), run()
    assert a == b and len(a) == 8


def test_reject_too_long_prompt():
    core = make_core()
    seq = core.add_request(greedy_request(list(range(200)), max_tokens=2))
    assert seq.is_finished and seq.finish_reason == FinishReason.LENGTH


def make_core_multi(decode_steps, num_pages=64, max_batch=8, **cfg_kw):
    config = EngineConfig(
        num_pages=num_pages, page_size=PAGE, max_batch_size=max_batch,
        max_prefill_tokens=256, max_seq_len=128, decode_steps=decode_steps, **cfg_kw,
    )
    runner = ModelRunner(
        CFG, PARAMS, num_pages=num_pages, page_size=PAGE,
        max_batch_size=max_batch, prefill_bucket=16, attn_impl="reference",
    )
    return EngineCore(runner, config)


def test_multi_step_decode_matches_single_step():
    # Fused 4-step decode bursts must be token-identical to per-step decode.
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7]]
    core = make_core_multi(decode_steps=4)
    for p in prompts:
        core.add_request(greedy_request(p, max_tokens=10))
    outputs = run_to_completion(core)
    for i, p in enumerate(prompts):
        assert outputs[i] == greedy_reference(p, 10), f"seq {i}"


def test_multi_step_decode_stop_token_discards_overshoot():
    prompt = [5, 6, 7]
    ref = greedy_reference(prompt, 8)
    stop_at = ref[2]
    core = make_core_multi(decode_steps=4)
    core.add_request(greedy_request(prompt, max_tokens=8, stop_token_ids=[stop_at]))
    outputs = run_to_completion(core)
    assert outputs[0] == ref[: ref.index(stop_at) + 1]
    assert outputs["finish"][0] == FinishReason.STOP


def test_multi_step_decode_odd_max_tokens():
    # max_tokens not a multiple of the burst size.
    prompt = [2, 4, 6]
    core = make_core_multi(decode_steps=4)
    core.add_request(greedy_request(prompt, max_tokens=6))
    outputs = run_to_completion(core)
    assert outputs[0] == greedy_reference(prompt, 6)
    assert outputs["finish"][0] == FinishReason.LENGTH


def test_pipelined_decode_midstream_admission():
    # A request admitted while a chained burst is in flight must be absorbed
    # cleanly (the overlap pipeline re-plans composition per step); both
    # sequences still match the greedy reference. decode_steps>1 pipelining
    # is served by the overlap path since the standalone burst pipeline
    # was folded into it.
    core = make_core_multi(decode_steps=4, overlap=True)
    p1, p2 = [1, 2, 3, 4, 5], [9, 8, 7]
    core.add_request(greedy_request(p1, max_tokens=12))
    # Fill the pipeline (prefill step + first dispatched burst + one chained).
    outputs = {}
    for _ in range(3):
        for seq, out in core.step():
            outputs.setdefault(seq.seq_id, []).extend(out.token_ids)
    assert core._inflight is not None
    core.add_request(greedy_request(p2, max_tokens=12))
    outputs = run_to_completion(core, outputs=outputs)
    assert outputs[0] == greedy_reference(p1, 12)
    assert outputs[1] == greedy_reference(p2, 12)


def test_pipelined_decode_cancellation_inflight():
    core = make_core_multi(decode_steps=4, overlap=True)
    ctx1, ctx2 = Context(), Context()
    core.add_request(greedy_request([1, 2, 3], max_tokens=40), ctx1)
    core.add_request(greedy_request([4, 5, 6], max_tokens=40), ctx2)
    outputs = {}
    for _ in range(3):
        for seq, out in core.step():
            outputs.setdefault(seq.seq_id, []).extend(out.token_ids)
    assert core._inflight is not None
    ctx1.stop_generating()
    outputs = run_to_completion(core, outputs=outputs)
    assert outputs["finish"][0] == FinishReason.CANCELLED
    # The surviving sequence still completes correctly.
    assert outputs[1] == greedy_reference([4, 5, 6], 40)
    assert core._inflight is None


def test_burst_overshoot_cannot_corrupt_live_pages():
    """Heterogeneous finish lines inside one fused burst: a sequence whose
    max_tokens ends mid-burst must not let the burst's overshoot KV writes
    land in live pages (they are masked to the null page). Everyone stays
    token-exact vs the step-by-step greedy reference, including a follow-up
    request that reuses the short sequence's cached prefix."""
    core = make_core_multi(decode_steps=8)
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 12, 13, 14]]
    budgets = [3, 17, 9]  # finish lines at different points within/across bursts
    for p, mt in zip(prompts, budgets):
        core.add_request(greedy_request(p, max_tokens=mt))
    outputs = run_to_completion(core)
    for i, (p, mt) in enumerate(zip(prompts, budgets)):
        assert outputs[i] == greedy_reference(p, mt), f"seq {i}"

    # The short sequence's pages are prefix cache now; a request extending
    # its prompt must see uncorrupted KV (token-exact again).
    ext = prompts[0] + outputs[0][:2]
    core.add_request(greedy_request(ext, max_tokens=6))
    out2 = run_to_completion(core)
    assert out2[3] == greedy_reference(ext, 6)
