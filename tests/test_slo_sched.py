"""SLO admission-control plane (ISSUE 9): EDF ordering, tenant quotas,
chunk-budget hysteresis, predictor fallback, and the router's attainment
term — plus the engine seams (flight fields, admission_wait_ms, and the
bit-identical FIFO guarantee when the plane is off)."""

import asyncio
from collections import deque

import pytest

from dynamo_tpu.engine.sequence import Sequence
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.sched import (
    AdmissionConfig,
    AdmissionController,
    ChunkBudgetController,
    TenantQuota,
    TenantRegistry,
    TtftPredictor,
)


def _req(tokens, *, tenant=None, priority=0, max_tokens=4):
    return PreprocessedRequest(
        token_ids=list(tokens),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        tenant_id=tenant,
        priority=priority,
    )


def _seq(seq_id, n_tokens, *, arrival, tenant=None, priority=0):
    seq = Sequence.from_request(
        seq_id, _req(range(1, n_tokens + 1), tenant=tenant, priority=priority),
        Context(), page_size=16, salt=0,
    )
    seq.arrival_time = arrival
    return seq


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# -- EDF ordering -------------------------------------------------------------


def test_edf_reorders_by_slack_not_arrival():
    """A tier-1 request that arrived FIRST sinks behind a later tier-0
    request: its stretched deadline gives it more slack. FIFO would never
    produce this order."""
    clk = _Clock()
    ctl = AdmissionController(
        AdmissionConfig(ttft_budget_s=0.5, tier_stretch=2.0),
        predictor=TtftPredictor(), tenants=TenantRegistry(clock=clk), clock=clk,
    )
    relaxed = _seq(0, 200, arrival=0.0, priority=1)  # deadline 0 + 0.5*2 = 1.0
    urgent = _seq(1, 20, arrival=0.1, priority=0)  # deadline 0.1 + 0.5 = 0.6
    waiting = deque([relaxed, urgent])  # arrival order
    admissible = ctl.prepare(waiting, running=0, slots=8)
    assert admissible == 2  # no quotas: everything is admissible
    assert [s.seq_id for s in waiting] == [1, 0]
    # prepare stamped each sequence's prediction for the feedback loop.
    assert all(s.predicted_ttft_s is not None and s.predicted_ttft_s > 0 for s in waiting)
    # last_slack_ms reflects the tightest (head) request.
    assert ctl.last_slack_ms == pytest.approx(
        (0.6 - urgent.predicted_ttft_s) * 1e3, rel=1e-6
    )


def test_edf_equal_slack_tie_breaks_on_arrival():
    clk = _Clock()
    ctl = AdmissionController(
        AdmissionConfig(ttft_budget_s=0.5, tier_stretch=2.0),
        predictor=TtftPredictor(), tenants=TenantRegistry(clock=clk), clock=clk,
    )
    # Same prompt (same prediction) and same 1.0 s deadline via different
    # tiers: tier-0 arriving at 0.5 vs tier-1 arriving at 0.0. Equal slack,
    # so the earlier arrival goes first.
    a = _seq(5, 32, arrival=0.5, priority=0)
    b = _seq(3, 32, arrival=0.0, priority=1)
    waiting = deque([a, b])
    ctl.prepare(waiting, running=0, slots=8)
    assert [s.seq_id for s in waiting] == [3, 5]


def test_tier_clamps_into_range():
    ctl = AdmissionController(predictor=TtftPredictor(), tenants=TenantRegistry())
    assert ctl.tier_of(_seq(0, 4, arrival=0.0, priority=-3)) == 0
    assert ctl.tier_of(_seq(1, 4, arrival=0.0, priority=99)) == ctl.config.max_tier


# -- tenant quotas ------------------------------------------------------------


def test_token_bucket_throttles_heavy_tenant_not_light():
    """Starvation protection: a heavy tenant flooding 10x its rate gets
    throttled (its requests sink behind every admissible one); the light
    tenant's requests are untouched. Borrow semantics admit the first
    oversized request instead of wedging."""
    clk = _Clock()
    reg = TenantRegistry(clock=clk)
    reg.configure("heavy", TenantQuota(rate_tokens_per_s=100.0, burst_tokens=100.0))
    ctl = AdmissionController(
        AdmissionConfig(ttft_budget_s=0.5), predictor=TtftPredictor(),
        tenants=reg, clock=clk,
    )
    heavies = [_seq(i, 100, arrival=i * 1e-3, tenant="heavy", priority=1) for i in range(10)]
    lights = [_seq(100 + i, 10, arrival=0.02 + i * 1e-3) for i in range(4)]
    waiting = deque(heavies + lights)
    admissible = ctl.prepare(waiting, running=0, slots=32)
    head = list(waiting)[:admissible]
    # One heavy request fits the (full) bucket; the other nine are throttled
    # behind every light request.
    assert admissible == 5
    assert sum(1 for s in head if s.request.tenant_id == "heavy") == 1
    assert sum(1 for s in head if s.request.tenant_id is None) == 4
    assert reg.throttled["heavy"] == 9
    assert "default" not in reg.throttled
    # Charge the admitted head like the engine would.
    for s in head:
        ctl.on_admit(s, clk())
    # Bucket is drained: nothing heavy clears the gate...
    rest = deque([s for s in heavies if s.seq_id not in {x.seq_id for x in head}])
    assert ctl.prepare(rest, running=5, slots=32) == 0
    # ...until the bucket refills (1 s at 100 tok/s = one 100-token prompt).
    clk.t += 1.0
    assert ctl.prepare(rest, running=5, slots=32) == 1
    # Deferred requests kept their EDF order (arrival, here).
    assert [s.seq_id for s in rest] == sorted(s.seq_id for s in rest)


def test_inflight_cap_never_wedges_an_idle_tenant():
    clk = _Clock()
    reg = TenantRegistry(clock=clk)
    reg.configure("t", TenantQuota(max_inflight_tokens=50))
    # Nothing in flight: even an oversized request is admissible (the cap
    # throttles concurrency, it must not deadlock the tenant outright).
    assert reg.would_admit("t", 80)
    reg.on_admit("t", 80)
    assert reg.inflight("t") == 80
    assert not reg.would_admit("t", 10)  # live + 10 > 50
    reg.on_finish("t", 80)
    assert reg.inflight("t") == 0
    assert reg.would_admit("t", 10)


def test_admission_charges_once_across_preemption():
    clk = _Clock()
    reg = TenantRegistry(clock=clk)
    reg.configure("t", TenantQuota(rate_tokens_per_s=100.0, burst_tokens=100.0))
    ctl = AdmissionController(predictor=TtftPredictor(), tenants=reg, clock=clk)
    seq = _seq(1, 60, arrival=0.0, tenant="t")
    ctl.on_admit(seq, 0.0)
    level_after = reg._bucket_level("t", reg.quota("t"))
    ctl.on_admit(seq, 0.0)  # preempted resume: must not double-charge
    assert reg._bucket_level("t", reg.quota("t")) == pytest.approx(level_after)
    assert reg.inflight("t") == 60
    ctl.on_finish(seq)
    assert reg.inflight("t") == 0
    ctl.on_finish(seq)  # idempotent
    assert reg.inflight("t") == 0


def test_preempted_resume_bypasses_quota_gate():
    """A preempted sequence's tokens are still charged (refunded only at
    on_finish), so prepare() must not re-gate it through would_admit — its
    own in-flight charge would count against it and, with an in-flight cap
    under 2x the prompt, wedge the request in waiting forever."""
    clk = _Clock()
    reg = TenantRegistry(clock=clk)
    reg.configure("t", TenantQuota(rate_tokens_per_s=10.0, burst_tokens=60.0,
                                   max_inflight_tokens=80))
    ctl = AdmissionController(predictor=TtftPredictor(), tenants=reg, clock=clk)
    seq = _seq(1, 60, arrival=0.0, tenant="t")
    ctl.on_admit(seq, 0.0)  # first admission: 60 tokens charged + bucket drained
    # Preempted back into waiting: live=60, live+60 > 80 and the bucket is
    # empty, yet the resume must be admissible (it holds what it charged).
    waiting = deque([seq])
    assert ctl.prepare(waiting, running=0, slots=8) == 1
    assert "t" not in reg.throttled
    # A *fresh* request from the same tenant still hits the gate.
    fresh = _seq(2, 60, arrival=0.0, tenant="t")
    waiting = deque([seq, fresh])
    assert ctl.prepare(waiting, running=0, slots=8) == 1
    assert [s.seq_id for s in waiting] == [1, 2]


def test_observe_uses_prediction_time_origin_not_arrival():
    """predicted_ttft_s is the *remaining* TTFT estimated at the last
    prepare(); the observation must share that time origin — measuring from
    arrival would fold already-elapsed queue wait into the ratio and inflate
    the predictor bias under load."""
    seen = []

    class _Rec(TtftPredictor):
        def observe(self, predicted_s, actual_s):
            seen.append((predicted_s, actual_s))
            super().observe(predicted_s, actual_s)

    clk = _Clock()
    ctl = AdmissionController(predictor=_Rec(), tenants=TenantRegistry(clock=clk), clock=clk)
    seq = _seq(1, 40, arrival=0.0)
    clk.t = 5.0  # 5 s of queue wait before the first EDF ordering
    ctl.prepare(deque([seq]), running=0, slots=8)
    assert seq.predicted_at == 5.0
    clk.t = 5.4
    ctl.on_first_token(seq)
    ((pred, actual),) = seen
    assert pred == seq.predicted_ttft_s
    assert actual == pytest.approx(0.4)  # not 5.4: same origin as the prediction


def test_tenant_registry_from_settings_json_overrides():
    from dynamo_tpu.config import TenantSettings

    reg = TenantRegistry.from_settings(TenantSettings(
        rate_tokens_per_s=10.0,
        quotas='{"heavy": {"rate_tokens_per_s": 1000, "burst_tokens": 500}}',
    ))
    assert reg.quota("anyone").rate_tokens_per_s == 10.0
    assert reg.quota("heavy").rate_tokens_per_s == 1000.0
    assert reg.quota("heavy").capacity == 500.0


# -- chunk-budget controller --------------------------------------------------


def test_chunk_controller_shrinks_relaxes_with_hysteresis():
    ctl = ChunkBudgetController(
        512, itl_budget_ms=50.0, floor_tokens=64,
        shrink_at=0.9, relax_at=0.5, cooldown_steps=2, window=16, min_samples=4,
    )
    assert ctl.budget() == 512
    # Tail at/over 0.9 * 50 ms: shrink (halve) once min_samples accumulate.
    for _ in range(4):
        ctl.observe(60.0)
    assert ctl.budget() == 256 and ctl.shrinks == 1
    # Post-change cooldown: the very next hot samples do not trigger a
    # second shrink until it has passed and fresh samples accumulate.
    ctl.observe(60.0)
    ctl.observe(60.0)
    assert ctl.budget() == 256
    for _ in range(4):
        ctl.observe(60.0)
    assert ctl.budget() == 128 and ctl.shrinks == 2
    # Keep shrinking under sustained pressure; never below the floor.
    for _ in range(40):
        ctl.observe(60.0)
    assert ctl.budget() == 64
    # Dead band (between relax_at and shrink_at): hold.
    for _ in range(20):
        ctl.observe(30.0)
    assert ctl.budget() == 64 and ctl.relaxes == 0
    # Slack (<= 0.5 * 50 ms): relax back up, capped at base.
    for _ in range(60):
        ctl.observe(10.0)
    assert ctl.budget() == 512 and ctl.relaxes == 3
    for _ in range(20):
        ctl.observe(10.0)
    assert ctl.budget() == 512  # never exceeds base


def test_chunk_controller_rejects_unchunked_base():
    with pytest.raises(ValueError):
        ChunkBudgetController(0)


# -- predictor ----------------------------------------------------------------


def test_predictor_fallback_monotone_and_online_corrected():
    p = TtftPredictor()  # no profile: pure service-time fallback
    small = p.predict(queued_tokens=100, running=0, slots=8)
    big = p.predict(queued_tokens=10000, running=0, slots=8)
    assert 0 < small < big  # monotone in queued work
    assert small == pytest.approx(100 / 20000.0)
    # Observed TTFT consistently 2x the prediction: the bias converges up
    # and later predictions inflate accordingly.
    for _ in range(50):
        p.observe(small, 2 * small)
    assert 1.5 < p.bias < 2.1
    assert p.predict(queued_tokens=100, running=0, slots=8) == pytest.approx(
        p.bias * 100 / 20000.0
    )
    # Clamps: one absurd observation cannot invert the queue order.
    p2 = TtftPredictor()
    p2.observe(0.001, 1000.0)  # raw ratio 1e6, clamped to 8 pre-EWMA
    assert p2.bias <= 1.0 + 0.2 * 8.0
    p2.observe(None, 1.0)  # no prediction recorded: ignored
    p2.observe(0.0, 1.0)
    assert p2.observations == 1


def test_predictor_uses_profile_surface():
    class Prof:
        prefill_tokens_per_sec = 10000.0

        def ttft_at(self, load, pct=99):
            return 0.1 + 0.4 * load

    p = TtftPredictor(Prof())
    idle = p.predict(queued_tokens=1000, running=0, slots=10)
    busy = p.predict(queued_tokens=1000, running=10, slots=10)
    assert idle == pytest.approx(0.1 + 1000 / 10000.0)
    assert busy == pytest.approx(0.5 + 1000 / 10000.0)


# -- router attainment term ---------------------------------------------------


def test_router_attainment_breaks_tie_toward_slack_worker():
    from dynamo_tpu.protocols.kv import ForwardPassMetrics
    from dynamo_tpu.router.indexer import OverlapScores
    from dynamo_tpu.router.scheduler import KvScheduler, SchedulerConfig

    class Prof:
        prefill_tokens_per_sec = 10000.0

        def ttft_at(self, load, pct=99):
            return 0.1 + 0.8 * load  # blows the 0.5 s budget above ~50% load

    def metrics(running):
        return ForwardPassMetrics(
            kv_active_blocks=1, kv_total_blocks=2, num_requests_waiting=0,
            num_requests_running=running, request_total_slots=8,
        )

    m = {1: metrics(8), 2: metrics(0)}  # equal base cost, unequal load
    base = KvScheduler(SchedulerConfig())
    costs = base.costs(4, OverlapScores(scores={}), m, [1, 2])
    assert costs[1] == pytest.approx(costs[2])
    assert base.select(costs) == 1  # argmin tie-break: lowest id
    armed = KvScheduler(SchedulerConfig(
        attainment_weight=1.0, ttft_slo_s=0.5, profile=Prof(),
    ))
    costs = armed.costs(4, OverlapScores(scores={}), m, [1, 2])
    assert costs[2] < costs[1]
    assert armed.select(costs) == 2
    # The hinge makes a predicted MISS hurt twice: worker 1 predicts 0.9 s
    # against a 0.5 s budget -> ratio + (ratio - 1).
    assert costs[1] - costs[2] == pytest.approx((0.9 / 0.5 + 0.9 / 0.5 - 1.0) - 0.1 / 0.5)
    # Staleness inflates the prediction: a quiet worker we have not heard
    # from loses its advantage.
    stale = armed.costs(4, OverlapScores(scores={}), m, [1, 2], staleness={2: 10.0})
    assert stale[2] > stale[1]


def test_configure_attainment_is_gated_on_master_toggle(monkeypatch):
    from dynamo_tpu.router.scheduler import SchedulerConfig
    from dynamo_tpu.sched import configure_attainment

    cfg = SchedulerConfig()
    monkeypatch.delenv("DYN_SLO_SCHED", raising=False)
    configure_attainment(cfg)
    assert cfg.attainment_weight == 0.0  # off: untouched
    monkeypatch.setenv("DYN_SLO_SCHED", "1")
    monkeypatch.setenv("DYN_SLO_SCHED_ATTAINMENT_WEIGHT", "2.5")
    monkeypatch.setenv("DYN_SLO_SCHED_TTFT_BUDGET_MS", "300")
    configure_attainment(cfg)
    assert cfg.attainment_weight == 2.5
    assert cfg.ttft_slo_s == pytest.approx(0.3)


# -- engine integration -------------------------------------------------------


def _mock_core(admission=None, **cfg_kw):
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.mocker import MockRunner

    kw = dict(
        num_pages=256, page_size=16, max_batch_size=8,
        max_prefill_tokens=4096, max_seq_len=8192,
        enable_prefix_caching=False, chunk_prefill_tokens=64,
    )
    kw.update(cfg_kw)
    cfg = EngineConfig(**kw)
    runner = MockRunner(num_pages=cfg.num_pages, page_size=cfg.page_size, realtime=False)
    return EngineCore(runner, cfg, admission=admission)


def test_engine_edf_serves_urgent_tier_before_relaxed_burst():
    """End to end on the real scheduler: a relaxed (tier-1) long prompt
    submitted FIRST is overtaken by a tier-0 short prompt; with the plane
    off the same scenario is strictly FIFO."""

    def scenario(admission):
        # max_prefill_tokens=512 so the 2048-token prompt spans several
        # steps — otherwise one step prefills both and order is invisible.
        core = _mock_core(admission=admission, max_prefill_tokens=512)
        heavy = core.add_request(_req(range(1, 2049), tenant="heavy", priority=1))
        light = core.add_request(_req(range(1, 33)))
        first = {}
        for step in range(400):
            if not core.has_work:
                break
            for seq, out in core.step():
                if out.token_ids and seq.seq_id not in first:
                    first[seq.seq_id] = step
        assert not core.has_work
        return heavy, light, first

    # tier_stretch=10 gives the tier-1 prompt enough deadline slack that
    # its larger predicted TTFT cannot win it the tighter slack anyway.
    heavy, light, first = scenario(AdmissionController(
        AdmissionConfig(ttft_budget_s=0.05, tier_stretch=10.0),
        predictor=TtftPredictor(), tenants=TenantRegistry(),
    ))
    assert first[light.seq_id] < first[heavy.seq_id]
    assert heavy.finish_reason is FinishReason.LENGTH  # relaxed, not starved
    heavy, light, first = scenario(None)  # FIFO: submission order wins
    assert first[heavy.seq_id] < first[light.seq_id]


def test_engine_flight_records_admission_fields_and_wait():
    from dynamo_tpu.observability.flight import STEP

    ctl = AdmissionController(predictor=TtftPredictor(), tenants=TenantRegistry())
    core = _mock_core(admission=ctl)
    core.add_request(_req(range(1, 100)))
    core.add_request(_req(range(1, 10)))
    waits = []
    for _ in range(200):
        if not core.has_work:
            break
        for seq, out in core.step():
            if out.admission_wait_ms is not None:
                waits.append((seq.seq_id, out.admission_wait_ms))
    steps = core.flight.snapshot(kind=STEP)
    assert steps, "no STEP records"
    for rec in steps:
        assert "admitted" in rec and "deferred" in rec and "deadline_slack_ms" in rec
    assert sum(r["admitted"] for r in steps) == 2
    # admission_wait_ms rides exactly the first delta of each request.
    assert sorted(sid for sid, _ in waits) == [0, 1]
    assert all(w >= 0 for _, w in waits)
    assert ctl.admitted_total == 2
    # Finished sequences released their quota charges.
    assert ctl.tenants.inflight("default") == 0
    assert not ctl._charges
    # The observed TTFTs closed the predictor's correction loop.
    assert ctl.predictor.observations == 2


def test_slo_sched_off_is_fifo_and_records_zeroes():
    """DYN_SLO_SCHED off: no controller is attached, the waiting queue is
    never reordered, chunk budget is the static config, and the new flight
    fields stay at their zero defaults."""
    from dynamo_tpu.observability.flight import STEP

    core = _mock_core()
    assert core.admission is None and core.chunk_controller is None
    assert core.chunk_budget_tokens() == 64
    a = core.add_request(_req(range(1, 50)))
    b = core.add_request(_req(range(1, 50)))
    assert [s.seq_id for s in core.waiting] == [a.seq_id, b.seq_id]
    first = {}
    saw_wait = []
    for step in range(200):
        if not core.has_work:
            break
        for seq, out in core.step():
            if out.token_ids and seq.seq_id not in first:
                first[seq.seq_id] = step
            saw_wait.append(out.admission_wait_ms)
    assert first[a.seq_id] <= first[b.seq_id]  # FIFO
    steps = core.flight.snapshot(kind=STEP)
    assert all(r["deadline_slack_ms"] == 0.0 for r in steps)
    # admission_wait_ms still reports (it is a measurement, not policy).
    assert any(w is not None for w in saw_wait)


def test_engine_builds_controllers_from_env(monkeypatch):
    monkeypatch.setenv("DYN_SLO_SCHED", "1")
    monkeypatch.setenv("DYN_SLO_SCHED_TTFT_BUDGET_MS", "200")
    monkeypatch.setenv("DYN_TENANT_RATE_TOKENS_PER_S", "123")
    core = _mock_core(slo_sched=True)
    assert core.admission is not None
    assert core.admission.config.ttft_budget_s == pytest.approx(0.2)
    assert core.admission.tenants.default_quota.rate_tokens_per_s == 123.0
    assert core.chunk_controller is not None
    assert core.chunk_controller.base == 64


def test_tenant_and_priority_cross_the_wire():
    req = _req(range(1, 5), tenant="acme", priority=2)
    d = req.to_dict()
    assert d["tenant_id"] == "acme" and d["priority"] == 2
    back = PreprocessedRequest.from_dict(d)
    assert back.tenant_id == "acme" and back.priority == 2
    # Legacy payloads (no fields) default clean.
    legacy = {k: v for k, v in d.items() if k not in ("tenant_id", "priority")}
    back = PreprocessedRequest.from_dict(legacy)
    assert back.tenant_id is None and back.priority == 0


def test_engine_metrics_export_admission_families():
    from dynamo_tpu.observability.metrics import EngineMetrics

    ctl = AdmissionController(predictor=TtftPredictor(), tenants=TenantRegistry())
    ctl.tenants.note_throttled("acme")
    core = _mock_core(admission=ctl)
    core.add_request(_req(range(1, 40), priority=1))
    m = EngineMetrics(worker="w0").bind_core(core)
    text = asyncio.run(m.render()).decode()
    assert 'dynamo_engine_admission_queue_depth{tier="1",worker="w0"} 1.0' in text
    assert 'dynamo_engine_deadline_misses_total{worker="w0"} 0.0' in text
    assert 'dynamo_tenant_throttled_total{tenant="acme",worker="w0"} 1.0' in text
    assert 'dynamo_engine_chunk_budget_tokens{worker="w0"} 64.0' in text
    # Plane off: tier-0 depth mirrors the waiting queue, families still export.
    core2 = _mock_core()
    core2.add_request(_req(range(1, 10)))
    m2 = EngineMetrics(worker="w1").bind_core(core2)
    text2 = asyncio.run(m2.render()).decode()
    assert 'dynamo_engine_admission_queue_depth{tier="0",worker="w1"} 1.0' in text2
    assert 'dynamo_engine_chunk_budget_tokens{worker="w1"} 64.0' in text2
