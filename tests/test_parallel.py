"""Sharded execution tests on the 8-device virtual CPU mesh.

Verifies the GSPMD path end-to-end: TP/DP-sharded engine steps produce
token-identical output to single-device execution, and the driver's
multichip dry-run entrypoints work.
"""

import jax
import numpy as np
import pytest

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.parallel.mesh import AXES, MeshPlan, make_mesh
from dynamo_tpu.parallel.sharding import param_shardings, shard_params
from tests.test_engine_core import greedy_reference, greedy_request, run_to_completion

CFG = PRESETS["test-tiny"]
PARAMS = llama.init_params(CFG, 0)
PAGE = 4


def test_mesh_plan_auto():
    assert MeshPlan.auto(8, num_kv_heads=2) == MeshPlan(dp=4, tp=2)
    assert MeshPlan.auto(8, num_kv_heads=8) == MeshPlan(dp=1, tp=8)
    assert MeshPlan.auto(1, num_kv_heads=8) == MeshPlan(dp=1, tp=1)
    # Wide-EP MoE: experts dominate.
    assert MeshPlan.auto(8, num_kv_heads=2, num_experts=8) == MeshPlan(dp=1, ep=8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    assert mesh.axis_names == AXES
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_param_shardings_cover_tree():
    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    sh = param_shardings(mesh, PARAMS)
    flat_p = jax.tree.leaves(PARAMS)
    flat_s = jax.tree.leaves(sh, is_leaf=lambda x: hasattr(x, "spec"))
    assert len(flat_p) == len(flat_s)
    placed = shard_params(PARAMS, mesh)
    # Sharded leaf: wq last dim split over tp=2.
    assert placed["layers"]["wq"].sharding.spec == sh["layers"]["wq"].spec


@pytest.mark.tpu_8
def test_sharded_engine_matches_single_device():
    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    runner = ModelRunner(
        CFG, PARAMS, num_pages=64, page_size=PAGE, max_batch_size=8,
        prefill_bucket=16, attn_impl="reference", mesh=mesh,
    )
    core = EngineCore(runner, EngineConfig(num_pages=64, page_size=PAGE, max_batch_size=8, max_seq_len=128))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 12, 13, 14], [2, 4, 6, 8, 10, 12]]
    for p in prompts:
        core.add_request(greedy_request(p, max_tokens=5))
    outputs = run_to_completion(core)
    for i, p in enumerate(prompts):
        assert outputs[i] == greedy_reference(p, 5), f"sharded mismatch for prompt {i}"


def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_graft_entry_single_chip_compiles(monkeypatch):
    monkeypatch.setenv("DYNAMO_ENTRY_PRESET", "test-tiny")  # 1B preset is too heavy for CPU CI
    import __graft_entry__ as ge

    fn, args = ge.entry()
    jitted = jax.jit(fn)
    out = jitted(*args)
    assert np.isfinite(np.asarray(out)).all()
