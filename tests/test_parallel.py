"""Sharded execution tests on the 8-device virtual CPU mesh.

Verifies the GSPMD path end-to-end: TP/DP-sharded engine steps produce
token-identical output to single-device execution, and the driver's
multichip dry-run entrypoints work.
"""

import jax
import numpy as np
import pytest

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.parallel.mesh import AXES, MeshPlan, make_mesh
from dynamo_tpu.parallel.sharding import param_shardings, shard_params
from tests.test_engine_core import greedy_reference, greedy_request, run_to_completion

CFG = PRESETS["test-tiny"]
PARAMS = llama.init_params(CFG, 0)
PAGE = 4


def test_mesh_plan_auto():
    assert MeshPlan.auto(8, num_kv_heads=2) == MeshPlan(dp=4, tp=2)
    assert MeshPlan.auto(8, num_kv_heads=8) == MeshPlan(dp=1, tp=8)
    assert MeshPlan.auto(1, num_kv_heads=8) == MeshPlan(dp=1, tp=1)
    # Wide-EP MoE: experts dominate.
    assert MeshPlan.auto(8, num_kv_heads=2, num_experts=8) == MeshPlan(dp=1, ep=8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    assert mesh.axis_names == AXES
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_param_shardings_cover_tree():
    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    sh = param_shardings(mesh, PARAMS)
    flat_p = jax.tree.leaves(PARAMS)
    flat_s = jax.tree.leaves(sh, is_leaf=lambda x: hasattr(x, "spec"))
    assert len(flat_p) == len(flat_s)
    placed = shard_params(PARAMS, mesh)
    # Sharded leaf: wq last dim split over tp=2.
    assert placed["layers"]["wq"].sharding.spec == sh["layers"]["wq"].spec


@pytest.mark.tpu_8
def test_sharded_engine_matches_single_device():
    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    runner = ModelRunner(
        CFG, PARAMS, num_pages=64, page_size=PAGE, max_batch_size=8,
        prefill_bucket=16, attn_impl="reference", mesh=mesh,
    )
    core = EngineCore(runner, EngineConfig(num_pages=64, page_size=PAGE, max_batch_size=8, max_seq_len=128))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 12, 13, 14], [2, 4, 6, 8, 10, 12]]
    for p in prompts:
        core.add_request(greedy_request(p, max_tokens=5))
    outputs = run_to_completion(core)
    for i, p in enumerate(prompts):
        assert outputs[i] == greedy_reference(p, 5), f"sharded mismatch for prompt {i}"


@pytest.mark.tpu_8
def test_sharded_engine_overlap_bit_identical():
    """The chained pipeline on a mesh runner (ISSUE 11 tentpole e —
    multi-chip is where dispatch latency hurts most): overlapped execution
    over dp×tp sharding stays token-identical to the synchronous sharded
    engine AND to the single-device greedy reference, chunked prefill and
    seeded sampling included."""
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    runner = ModelRunner(
        CFG, PARAMS, num_pages=64, page_size=PAGE, max_batch_size=8,
        prefill_bucket=16, attn_impl="reference", mesh=mesh,
    )

    def reqs():
        return [
            greedy_request([1, 2, 3, 4, 5], max_tokens=6, ignore_eos=True),
            greedy_request([9, 8, 7], max_tokens=8, ignore_eos=True),
            PreprocessedRequest(
                token_ids=[2, 4, 6, 8, 10, 12, 3, 5, 7, 9, 11, 13, 2, 4, 6, 8, 1, 2],
                sampling=SamplingOptions(temperature=0.7, seed=21),
                stop=StopConditions(max_tokens=8, ignore_eos=True),
            ),
        ]

    def run(overlap):
        core = EngineCore(runner, EngineConfig(
            num_pages=64, page_size=PAGE, max_batch_size=8, max_seq_len=128,
            chunk_prefill_tokens=8, overlap=overlap,
        ))
        for r in reqs():
            core.add_request(r)
        return run_to_completion(core), core

    base, _ = run(False)
    over, core = run(True)
    assert over == base
    assert core.overlap_step_counts["overlapped"] > 0  # the mesh path chained
    assert core.allocator.stats().active_pages == 0
    assert base[0] == greedy_reference([1, 2, 3, 4, 5], 6)


@pytest.mark.tpu_8
def test_sharded_engine_spec_overlap_bit_identical():
    """spec_k>0 + DYN_OVERLAP=1 on the mesh runner: the async verify
    (spec_step_async) dispatches through the same batch-sharded put path as
    the plain chained step, so overlapped speculation on a dp×tp mesh must
    stay token-identical to the non-speculative synchronous sharded engine
    — with both speculation and the pipeline actually engaged."""
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    runner = ModelRunner(
        CFG, PARAMS, num_pages=64, page_size=PAGE, max_batch_size=8,
        prefill_bucket=16, attn_impl="reference", mesh=mesh,
    )

    def reqs():
        return [  # periodic prompts so the prompt-lookup drafter engages
            greedy_request([5, 7, 5, 7, 5, 7, 9, 11], max_tokens=12, ignore_eos=True),
            PreprocessedRequest(
                token_ids=[2, 4, 2, 4, 2, 4, 6, 8],
                sampling=SamplingOptions(temperature=0.7, seed=21, logprobs=2),
                stop=StopConditions(max_tokens=10, ignore_eos=True),
            ),
        ]

    def run(overlap, spec_k):
        core = EngineCore(runner, EngineConfig(
            num_pages=64, page_size=PAGE, max_batch_size=8, max_seq_len=128,
            chunk_prefill_tokens=8, overlap=overlap, spec_k=spec_k,
        ))
        for r in reqs():
            core.add_request(r)
        return run_to_completion(core), core

    base, _ = run(False, 0)
    over, core = run(True, 3)
    assert over == base
    assert core.spec_tokens_proposed > 0  # speculation engaged...
    assert core.overlap_step_counts["overlapped"] > 0  # ...and still pipelined
    assert core.allocator.stats().active_pages == 0


def test_mrope_forward_sharded_matches_single_device():
    """Qwen2-VL M-RoPE shards like everything else: the same 3D-rope
    forward under a dp*tp mesh reproduces the single-device logits (the
    sectioned rope is elementwise per head slice, so tp must be exact)."""
    import dataclasses

    import jax.numpy as jnp

    cfg = dataclasses.replace(
        PRESETS["test-tiny"], mrope_section=(2, 3, 3), image_token_id=250,
    )
    params = llama.init_params(cfg, 3)
    b, t, ps = 2, 8, 4
    tokens = jnp.asarray(np.random.default_rng(0).integers(1, 200, (b, t)), jnp.int32)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None], (b, 1))
    # Divergent 3D coords (as an image span would produce).
    pos3 = jnp.stack([positions, positions // 2, positions % 3], axis=1)
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    slots = jnp.take_along_axis(tables, positions // ps, axis=1) * ps + positions % ps
    last = jnp.full((b,), t - 1, jnp.int32)

    def fwd(p):
        kc, vc = llama.init_kv_cache(cfg, num_pages=8, page_size=ps)
        logits, _, _ = llama.forward(
            p, cfg, tokens, positions, kc, vc, tables, slots, last,
            attn_impl="reference", mrope_positions=pos3,
        )
        return logits

    want = np.asarray(fwd(params))
    # tp <= num_kv_heads (test-tiny has 2): the documented GQA invariant.
    mesh = make_mesh(MeshPlan(dp=4, tp=2), jax.devices())
    placed = shard_params(params, mesh)
    got = np.asarray(jax.jit(fwd)(placed))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    # The 3D coords actually mattered (not silently 1D).
    base = np.asarray(
        jax.jit(lambda p: llama.forward(
            p, cfg, tokens, positions, *llama.init_kv_cache(cfg, 8, ps),
            tables, slots, last, attn_impl="reference",
        )[0])(placed)
    )
    assert not np.allclose(got, base)


def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_graft_entry_single_chip_compiles(monkeypatch):
    monkeypatch.setenv("DYNAMO_ENTRY_PRESET", "test-tiny")  # 1B preset is too heavy for CPU CI
    import __graft_entry__ as ge

    fn, args = ge.entry()
    jitted = jax.jit(fn)
    out = jitted(*args)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.tpu_8
def test_sp_ring_prefill_matches_single_device():
    """Prefill runs sequence-parallel ring attention (sp axis) and must be
    token-identical to single-device paged prefill; decode then continues on
    the paged path against the ring-written cache."""
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2), jax.devices())
    runner = ModelRunner(
        CFG, PARAMS, num_pages=64, page_size=PAGE, max_batch_size=8,
        prefill_bucket=16, attn_impl="reference", mesh=mesh,
    )
    core = EngineCore(
        runner,
        EngineConfig(num_pages=64, page_size=PAGE, max_batch_size=8, max_seq_len=128,
                     enable_prefix_caching=False),
    )
    prompts = [list(range(1, 17)), [9, 8, 7, 6, 5, 4, 3, 2, 1, 2, 3, 4]]
    for p in prompts:
        core.add_request(greedy_request(p, max_tokens=6))
    outputs = run_to_completion(core)
    for i, p in enumerate(prompts):
        assert outputs[i] == greedy_reference(p, 6), f"seq {i}"


def test_select_impl_ring_conditions():
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2), jax.devices())
    runner = ModelRunner(
        CFG, PARAMS, num_pages=64, page_size=PAGE, max_batch_size=8,
        prefill_bucket=16, attn_impl="reference", mesh=mesh,
    )
    import numpy as np
    from dynamo_tpu.engine.runner import StepBatch

    def batch(t, pos0):
        b = 2
        return StepBatch(
            tokens=np.zeros((b, t), np.int32),
            positions=np.tile(np.arange(pos0, pos0 + t, dtype=np.int32), (b, 1)),
            block_tables=np.zeros((b, 4), np.int32),
            slot_mapping=np.zeros((b, t), np.int32),
            last_token_index=np.zeros(b, np.int32),
            temperature=np.zeros(b, np.float32),
            top_k=np.zeros(b, np.int32),
            top_p=np.ones(b, np.float32),
            seeds=np.zeros(b, np.uint32),
            sample_steps=np.zeros(b, np.int32),
            freq_pen=np.zeros(b, np.float32),
            pres_pen=np.zeros(b, np.float32),
            pos_limit=np.full(b, 10**9, np.int32),
            history=np.full((b, 1), -1, np.int32),
        )

    assert runner._select_impl(batch(16, 0)) == "ring"      # whole-prompt prefill
    assert runner._select_impl(batch(1, 5)) == "reference"  # decode
    assert runner._select_impl(batch(16, 8)) == "reference" # chunk continuation
    assert runner._select_impl(batch(15, 0)) == "reference" # not sp-divisible
