"""Native kernel tests: the C++ chained-hash module vs the Python reference.

The extension is optional (built via `make -C native`); when absent the
Python fallback serves, and the parity tests build it on the fly.
"""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _ensure_built():
    try:
        from dynamo_tpu import _dyncore  # noqa: F401
        return True
    except ImportError:
        pass
    r = subprocess.run(["make", "-C", str(REPO / "native")], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"native build unavailable: {r.stdout}{r.stderr}")
    return True


def test_native_hash_parity_with_python():
    _ensure_built()
    from dynamo_tpu import _dyncore
    from dynamo_tpu.tokens import DEFAULT_SALT, hash_token_block

    rng = np.random.default_rng(0)
    for n, bs, salt in [(16, 16, DEFAULT_SALT), (515, 16, DEFAULT_SALT), (64, 4, 123456789),
                        (4096, 16, DEFAULT_SALT ^ 0xABCDEF), (3, 16, DEFAULT_SALT)]:
        toks = rng.integers(0, 2**31 - 1, n).astype("<i4")
        native = _dyncore.block_hashes(toks[: (n // bs) * bs].tobytes(), bs, salt)
        parent = None
        expected = []
        for i in range(n // bs):
            h = hash_token_block(toks[i * bs:(i + 1) * bs], parent, salt=salt)
            expected.append(h)
            parent = h
        assert native == expected, (n, bs)


def test_compute_block_hashes_uses_native_consistently():
    """The public API must give identical chains whichever backend serves it
    (router and engine compare these values across processes)."""
    _ensure_built()
    import dynamo_tpu.tokens as T
    from dynamo_tpu import _dyncore

    toks = list(range(1, 200))
    saved = T._dyncore
    try:
        # Force the native path even if tokens.py was imported pre-build.
        T._dyncore = _dyncore
        with_native = T.compute_block_hashes(toks, 16)
        T._dyncore = None
        pure = T.compute_block_hashes(toks, 16)
    finally:
        T._dyncore = saved
    assert with_native == pure
    # A partial trailing block is excluded identically on both paths.
    assert len(with_native) == 199 // 16


def test_native_rejects_bad_input():
    _ensure_built()
    from dynamo_tpu import _dyncore

    with pytest.raises(ValueError):
        _dyncore.block_hashes(b"\x00\x01\x02", 16, 0)  # not i32-aligned
    with pytest.raises(ValueError):
        _dyncore.block_hashes(b"\x00" * 64, 0, 0)  # bad block size
    assert _dyncore.block_hashes(b"", 16, 0) == []


def test_native_parent_chaining():
    _ensure_built()
    from dynamo_tpu import _dyncore
    from dynamo_tpu.tokens import hash_token_block

    toks = np.arange(32, dtype="<i4")
    root_chain = _dyncore.block_hashes(toks.tobytes(), 16, 7)
    # Supplying the first hash as parent for the second half reproduces it.
    tail = _dyncore.block_hashes(toks[16:].tobytes(), 16, 7, parent=root_chain[0])
    assert tail == [root_chain[1]]
    assert root_chain[0] == hash_token_block(toks[:16], None, salt=7)
