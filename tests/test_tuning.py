"""Auto-tuner tests: search convergence, journal resume, profile precedence,
objective scoring, the loss-snapshot API, and the end-to-end mock-probe smoke
(the ``tune`` marker tier)."""

import json
import os

import pytest

from dynamo_tpu.config import TuneSettings
from dynamo_tpu.tuning import (
    BURN_DOWN_TARGET,
    KNOBS,
    Tuner,
    apply_profile,
    burn_down,
    default_assignment,
    get_knob,
    load_profile,
    make_profile,
    save_profile,
    score_trial,
    select_knobs,
)
from dynamo_tpu.tuning.probe import env_overlay
from dynamo_tpu.tuning.search import TrialJournal
from dynamo_tpu.tuning.space import assignment_env, validate_assignment

# ---------------------------------------------------------------- knob space


def test_knob_registry_shape():
    names = [k.name for k in KNOBS]
    assert len(set(names)) == len(names)
    envs = [k.env for k in KNOBS]
    assert len(set(envs)) == len(envs)
    for knob in KNOBS:
        assert knob.default in knob.candidates
        assert knob.env.startswith("DYN_")
        assert knob.doc


def test_select_knobs_hardware_filter():
    mock_knobs = select_knobs(hardware=False)
    assert all(not k.hardware_only for k in mock_knobs)
    assert {k.name for k in mock_knobs} == {
        "chunk_prefill_tokens", "decode_steps", "spec_k"
    }
    # An explicit name list overrides the hardware filter (loop tests can
    # force-sweep a hardware knob on the CPU proxy).
    forced = select_knobs("decode_splits,spec_k", hardware=False)
    assert [k.name for k in forced] == ["decode_splits", "spec_k"]


def test_validate_assignment_rejects_off_ladder():
    with pytest.raises(ValueError, match="not on its ladder"):
        validate_assignment({"decode_steps": 3})
    with pytest.raises(KeyError, match="unknown knob"):
        validate_assignment({"warp_speed": 11})


def test_env_overlay_restores_exactly(monkeypatch):
    monkeypatch.setenv("DYN_WORKER_DECODE_STEPS", "7")
    monkeypatch.delenv("DYN_WORKER_SPEC_K", raising=False)
    with env_overlay({"decode_steps": 4, "spec_k": 2}):
        assert os.environ["DYN_WORKER_DECODE_STEPS"] == "4"
        assert os.environ["DYN_WORKER_SPEC_K"] == "2"
    assert os.environ["DYN_WORKER_DECODE_STEPS"] == "7"
    assert "DYN_WORKER_SPEC_K" not in os.environ


# ----------------------------------------------------------------- objective


def test_score_trial_is_throughput_when_within_budgets():
    score, breakdown = score_trial(
        {"tok_per_sec": 1234.0, "itl_p99_ms": 10.0, "ttft_p50_ms": 100.0, "loss": {}}
    )
    assert score == 1234.0
    assert breakdown["itl_factor"] == 1.0
    assert breakdown["ttft_factor"] == 1.0
    assert breakdown["burn_factor"] == 1.0


def test_score_trial_discounts_tail_overshoot():
    score, breakdown = score_trial(
        {"tok_per_sec": 1000.0, "itl_p99_ms": 100.0, "ttft_p50_ms": 0.0, "loss": {}}
    )
    assert breakdown["itl_factor"] == 0.5
    assert score == 500.0


def test_score_trial_discounts_burnable_loss():
    loss = {
        "step_time_ms": {"wall": 900.0, "dispatch": 800.0, "gap": 100.0},
        "lost_time_ms": {"gap": 100.0, "queue": 500.0},
    }
    score, breakdown = score_trial(
        {"tok_per_sec": 1000.0, "itl_p99_ms": 0.0, "ttft_p50_ms": 0.0, "loss": loss}
    )
    # gap is burnable (100/1000 of the timeline); queue prices load, not
    # knobs, and must not discount the trial.
    assert breakdown["burnable_frac"] == 0.1
    assert breakdown["burn_factor"] == pytest.approx(1.0 - (0.1 - BURN_DOWN_TARGET))
    assert score == pytest.approx(950.0)


def test_burn_down_target_and_met():
    ok = burn_down({
        "step_time_ms": {"wall": 1000.0, "gap": 0.0},
        "lost_time_ms": {"gap": 10.0},
    })
    assert ok["met"] and ok["burnable_frac"] == pytest.approx(0.01)
    bad = burn_down({
        "step_time_ms": {"wall": 1000.0, "gap": 0.0},
        "lost_time_ms": {"gap": 200.0, "spec": 100.0},
    })
    assert not bad["met"] and bad["burnable_frac"] == pytest.approx(0.3)
    assert bad["target"] == BURN_DOWN_TARGET
    # Degenerate empty snapshot: no wall, nothing burnable, target met.
    assert burn_down({})["met"]


# ---------------------------------------------------- search on a synthetic
# objective with a planted optimum: separable quadratic over ladder indices.

OPTIMUM = {"chunk_prefill_tokens": 256, "decode_steps": 4, "spec_k": 2}


def quadratic_probe(assignment, requests):
    dist = sum(
        (get_knob(n).candidates.index(assignment[n])
         - get_knob(n).candidates.index(opt)) ** 2
        for n, opt in OPTIMUM.items()
    )
    return {
        "tok_per_sec": 1000.0 - 100.0 * dist,
        "itl_p99_ms": 0.0,
        "ttft_p50_ms": 0.0,
        "loss": {},
    }


def _settings(out_dir, **kw):
    base = dict(mode="mock", seed=0, rounds=3, requests=16, out_dir=str(out_dir))
    base.update(kw)
    return TuneSettings(**base)


def test_search_converges_to_planted_optimum(tmp_path):
    tuner = Tuner(_settings(tmp_path), probe_fn=quadratic_probe)
    report = tuner.run()
    assert report["best"]["assignment"] == dict(sorted(OPTIMUM.items()))
    # Defaults (512, 1, 0) sit at squared ladder distance 6 -> score 400;
    # the optimum scores 1000.
    assert report["baseline"]["score"] == 400.0
    assert report["best"]["score"] == 1000.0
    assert report["gain"] == 2.5
    assert report["stopped"] == "plateau"
    assert [h["knob"] for h in report["history"]] == [
        "chunk_prefill_tokens", "decode_steps", "spec_k"
    ]


def test_search_is_deterministic_and_bounded(tmp_path):
    def run(out_dir):
        calls = []

        def counting_probe(assignment, requests):
            calls.append((dict(sorted(assignment.items())), requests))
            return quadratic_probe(assignment, requests)

        tuner = Tuner(_settings(out_dir), probe_fn=counting_probe)
        report = tuner.run()
        return report, calls

    report_a, calls_a = run(tmp_path / "a")
    report_b, calls_b = run(tmp_path / "b")
    assert calls_a == calls_b  # identical trial sequence, not just winner
    assert report_a["best"]["assignment"] == report_b["best"]["assignment"]
    assert report_a["trials_measured"] == report_b["trials_measured"]
    # Bounded: far under exhaustive (4*4*3=48 full-length trials) even
    # before dedup -- halving measures at most ~half the rungs full-length.
    assert report_a["trials_measured"] <= 40


def test_search_budget_stop_still_writes_artifacts(tmp_path):
    tuner = Tuner(_settings(tmp_path, max_trials=3), probe_fn=quadratic_probe)
    report = tuner.run()
    assert report["stopped"] == "budget"
    assert report["trials_measured"] == 3
    assert os.path.exists(report["profile_path"])
    assert os.path.exists(report["report_path"])
    with open(report["journal_path"]) as f:
        assert sum(1 for line in f if line.strip()) == 3


# ------------------------------------------------------------------ journal


def test_trial_journal_roundtrip(tmp_path):
    path = tmp_path / "journal.jsonl"
    journal = TrialJournal(path)
    assert journal.loaded == 0
    entry = {
        "key": TrialJournal.key({"decode_steps": 4}, 16),
        "assignment": {"decode_steps": 4},
        "requests": 16,
        "score": 1.5,
    }
    journal.record(entry)
    assert journal.lookup({"decode_steps": 4}, 16) == entry
    assert journal.lookup({"decode_steps": 4}, 8) is None
    reloaded = TrialJournal(path)
    assert reloaded.loaded == 1
    assert reloaded.lookup({"decode_steps": 4}, 16) == entry


def test_journal_key_is_order_insensitive():
    a = TrialJournal.key({"spec_k": 2, "decode_steps": 4}, 16)
    b = TrialJournal.key({"decode_steps": 4, "spec_k": 2}, 16)
    assert a == b
    assert TrialJournal.key({"spec_k": 2}, 16) != TrialJournal.key({"spec_k": 2}, 8)


def test_resume_replays_journal_without_remeasuring(tmp_path):
    first = Tuner(_settings(tmp_path), probe_fn=quadratic_probe)
    report_first = first.run()
    assert report_first["trials_measured"] > 0

    def forbidden_probe(assignment, requests):
        raise AssertionError("resume must replay the journal, not re-measure")

    resumed = Tuner(_settings(tmp_path), probe_fn=forbidden_probe)
    report_resumed = resumed.run()
    assert report_resumed["trials_measured"] == 0
    assert report_resumed["trials_cached"] > 0
    assert report_resumed["best"]["assignment"] == report_first["best"]["assignment"]
    assert report_resumed["best"]["score"] == report_first["best"]["score"]


# ------------------------------------------------------------------ profile


def test_profile_roundtrip(tmp_path):
    profile = make_profile(
        OPTIMUM, preset="test-tiny", mode="mock", platform="cpu",
        score=1000.0, baseline_score=400.0, meta={"seed": 0},
    )
    assert profile["gain"] == 2.5
    assert profile["env"] == assignment_env(OPTIMUM)
    path = tmp_path / "profile.json"
    save_profile(path, profile)
    assert load_profile(path) == profile


def test_load_profile_rejects_bad_documents(tmp_path):
    bad_version = tmp_path / "v99.json"
    bad_version.write_text(json.dumps({"version": 99, "env": {}}))
    with pytest.raises(ValueError, match="unsupported profile version"):
        load_profile(bad_version)
    no_env = tmp_path / "noenv.json"
    no_env.write_text(json.dumps({"version": 1}))
    with pytest.raises(ValueError, match="no 'env' assignment map"):
        load_profile(no_env)


def test_apply_profile_precedence_env_cli_profile():
    profile = make_profile(
        OPTIMUM, preset="test-tiny", mode="mock", platform="cpu",
        score=1.0, baseline_score=1.0,
    )
    env = {"DYN_WORKER_DECODE_STEPS": "8"}  # operator env wins
    applied = apply_profile(
        profile, env=env, cli_set={"DYN_WORKER_SPEC_K"},  # CLI wins too
    )
    assert applied == {"DYN_WORKER_CHUNK_PREFILL_TOKENS": "256"}
    assert env["DYN_WORKER_DECODE_STEPS"] == "8"  # untouched
    assert env["DYN_WORKER_CHUNK_PREFILL_TOKENS"] == "256"
    assert "DYN_WORKER_SPEC_K" not in env


# ----------------------------------------------------- loss-snapshot API


def test_loss_snapshot_stable_keys_on_mock_core():
    from dynamo_tpu.engine.core import EngineConfig
    from dynamo_tpu.mocker import build_mock_core
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )

    cfg = EngineConfig(
        num_pages=64, page_size=16, max_batch_size=4, max_seq_len=256,
        enable_prefix_caching=False,
    )
    core = build_mock_core(
        cfg, decode_us_base=50.0, decode_us_per_seq=5.0,
        prefill_us_per_token=1.0,
    )
    empty = core.loss_snapshot()
    assert empty["steps_total"] == 0
    assert empty["loss_coverage_frac"] == 1.0

    for _ in range(2):
        core.add_request(PreprocessedRequest(
            token_ids=list(range(1, 9)),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
        ))
    while core.has_work:
        core.step()

    snap = core.loss_snapshot()
    assert set(snap) == {
        "lost_time_ms", "step_time_ms", "step_kind_counts", "steps_total",
        "overlap_step_counts", "overlap_barrier_counts",
        "noncompute_wall_ms", "loss_coverage_frac",
    }
    assert set(snap["step_time_ms"]) == {"wall", "dispatch", "gap"}
    assert snap["steps_total"] == sum(snap["step_kind_counts"].values())
    assert snap["steps_total"] > 0
    assert set(snap["step_kind_counts"]) <= {"mixed", "prefill", "decode", "drain"}
    assert snap["step_time_ms"]["wall"] > 0.0
    assert snap["noncompute_wall_ms"] >= 0.0
    assert 0.0 <= snap["loss_coverage_frac"] <= 1.0
    # The snapshot is a copy: mutating it must not touch the engine ledger.
    snap["lost_time_ms"]["gap"] = -1.0
    assert core.loss_snapshot()["lost_time_ms"].get("gap") != -1.0


# ---------------------------------------------------------- end-to-end smoke


@pytest.mark.tune
def test_tune_smoke_real_mock_probe(tmp_path, monkeypatch):
    """The whole loop against the real CPU-proxy probe, budget-capped."""
    from dynamo_tpu.tuning.metrics import TunerMetrics

    monkeypatch.setenv("DYN_MOCK_PREFILL_US_PER_TOKEN", "2")
    monkeypatch.setenv("DYN_MOCK_DECODE_US_BASE", "200")
    monkeypatch.setenv("DYN_MOCK_DECODE_US_PER_SEQ", "20")
    settings = _settings(
        tmp_path, requests=4, isl=24, osl=8, rounds=1, max_trials=3,
    )
    metrics = TunerMetrics()
    report = Tuner(settings, metrics=metrics).run()
    assert report["stopped"] == "budget"
    assert report["trials_measured"] == 3
    assert report["baseline"]["score"] > 0.0
    assert report["baseline"]["metrics"]["generated_tokens"] == 4 * 8
    assert "loss" in report["baseline"]["metrics"]
    assert os.path.exists(report["journal_path"])
    assert os.path.exists(report["profile_path"])
    assert load_profile(report["profile_path"])["mode"] == "mock"
    text = metrics.render().decode()
    assert 'dynamo_tuner_trials_total{mode="mock",preset="test-tiny"} 3.0' in text


@pytest.mark.tune
def test_tune_cli_main(tmp_path, monkeypatch, capsys):
    from dynamo_tpu.tuning.__main__ import main

    monkeypatch.setenv("DYN_MOCK_PREFILL_US_PER_TOKEN", "2")
    monkeypatch.setenv("DYN_MOCK_DECODE_US_BASE", "200")
    monkeypatch.setenv("DYN_MOCK_DECODE_US_PER_SEQ", "20")
    rc = main([
        "--requests", "4", "--isl", "16", "--osl", "6", "--rounds", "1",
        "--max-trials", "2", "--out-dir", str(tmp_path),
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["stopped"] == "budget"
    assert summary["trials_measured"] == 2
    assert summary["baseline_score"] > 0.0
    assert os.path.exists(summary["journal"])


def test_default_assignment_matches_untuned_defaults():
    mock_knobs = select_knobs(hardware=False)
    assert default_assignment(mock_knobs) == {
        "chunk_prefill_tokens": 512, "decode_steps": 1, "spec_k": 0,
    }
