"""Test configuration: force an 8-device virtual CPU mesh.

All tests run on CPU with 8 virtual XLA devices so that sharding/multi-chip
logic (TP/DP/EP/SP meshes, collectives, disaggregated prefill/decode transfer)
is exercised without TPU hardware. Benchmarks (`bench.py`) run on the real
chip instead.
"""

import os

# Must be set before jax is imported anywhere. Note: the environment may pin
# JAX_PLATFORMS to a hardware plugin (e.g. the axon TPU tunnel) which would
# otherwise win — force-assign AND set the config flag after import.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# XLA:CPU on AMX machines runs f32 matmuls through a bf16-class fast path
# by default (measured 2.6e-3 error on a 192-dot); golden-parity tests need
# real f32. Applies to tests only — TPU serving precision is configured by
# the ops themselves (preferred_element_type etc.).
jax.config.update("jax_default_matmul_precision", "highest")

# Persistent XLA compile cache: jit compiles dominate suite wall time, and
# the programs are identical run to run. ~4x faster warm suite; the fast
# tier (-m fast) depends on this to stay under its budget.
_cache_dir = os.environ.get(
    "DYNAMO_TEST_COMPILE_CACHE", os.path.expanduser("~/.cache/dynamo_tpu_test_xla")
)
if _cache_dir != "0":
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

# The device-cost plane (observability/cost.py) is default-ON in production,
# but its background extraction thread re-lowers and re-compiles every
# dispatched program — duplicate compile work that the full suite pays in
# every runner/engine test and that pushes it past the tier-1 wall budget on
# CPU. Default it off for tests; test_cost_plane.py (and the bench probe
# structure test) opt back in explicitly.
os.environ.setdefault("DYN_COST_PLANE", "0")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

# The fast CI tier: modules whose tests are quick (no big jit programs, no
# multi-process spawns, no soak loops). `pytest -m fast` must stay a
# pre-commit-sized run (< 3 min cold, seconds warm); anything slower lives
# in the default tier. Module granularity keeps the list maintainable.
FAST_MODULES = {
    "test_blocks", "test_config_logging", "test_deploy", "test_gguf",
    "test_kubernetes_backend", "test_loader", "test_model_card",
    "test_native", "test_persist", "test_pipeline",
    "test_planner_connector", "test_preprocess_backend", "test_protocols",
    "test_pull_transfer", "test_router", "test_rope_convention",
    "test_runtime_component", "test_runtime_discovery",
    "test_runtime_transport", "test_sampling", "test_sentencepiece",
    "test_stall_free", "test_tokens", "test_tool_calls",
    "test_tracing_objects",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        module = item.nodeid.split("::")[0].rsplit("/", 1)[-1].removesuffix(".py")
        if module in FAST_MODULES and not any(
            m.name in ("e2e", "slow", "tpu_1", "tpu_8") for m in item.iter_markers()
        ):
            item.add_marker(pytest.mark.fast)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """The golden-logit suites (tests/test_golden*.py) are the strongest
    correctness evidence in the repo and silently importorskip when HF
    torch/transformers are missing — surface that loudly instead of letting
    the evidence vanish without a failure (VERDICT r3 weak #8)."""
    skipped = [
        rep for rep in terminalreporter.stats.get("skipped", [])
        if "test_golden" in str(getattr(rep, "nodeid", ""))
    ]
    if skipped:
        terminalreporter.write_sep(
            "!",
            f"WARNING: {len(skipped)} golden-parity tests SKIPPED "
            f"(torch/transformers unavailable?) — the HF-parity evidence "
            f"did not run",
            red=True,
        )


# Every jit-compiled executable maps JIT code pages that stay mapped for
# the life of the LoadedExecutable. Across the full suite that accumulates
# to ~65k VMAs and trips vm.max_map_count, at which point XLA's next mmap
# fails and executable deserialization segfaults. Drop the accumulated
# executables between modules once the map count gets close; the persistent
# on-disk compile cache makes the re-loads cheap (deserialize, not compile).
_MAP_COUNT_CLEAR_THRESHOLD = 40_000


def _vma_count() -> int:
    try:
        with open("/proc/self/maps", "rb") as f:
            return sum(1 for _ in f)
    except OSError:  # non-Linux: no /proc, no max_map_count to trip
        return 0


@pytest.fixture(scope="module", autouse=True)
def _bound_jit_executable_maps():
    yield
    if _vma_count() > _MAP_COUNT_CLEAR_THRESHOLD:
        import gc

        jax.clear_caches()
        gc.collect()


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio (no pytest-asyncio in this image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=60))
        return True
    return None


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


async def start_stack(model="test-tiny", **kw):
    """Serve ``model`` in-process; returns (handles, base_url). Shared by
    the HTTP-level e2e tests — keep teardown in stop_stack so handle-shape
    changes touch one place."""
    from dynamo_tpu.launch import run_local

    kw.setdefault("num_pages", 64)
    kw.setdefault("max_batch_size", 8)
    handles = await run_local(model, port=0, **kw)
    return handles, f"http://127.0.0.1:{handles['port']}"


async def stop_stack(handles):
    await handles["http"].stop()
    await handles["watcher"].close()
    for s in handles["services"]:
        await s.close()
    await handles["runtime"].close()


async def wait_for(cond, timeout=5.0, interval=0.05):
    """Poll ``cond()`` until truthy or timeout; returns whether it held."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        if cond():
            return True
        if loop.time() > deadline:
            return False
        await asyncio.sleep(interval)
