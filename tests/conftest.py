"""Test configuration: force an 8-device virtual CPU mesh.

All tests run on CPU with 8 virtual XLA devices so that sharding/multi-chip
logic (TP/DP/EP/SP meshes, collectives, disaggregated prefill/decode transfer)
is exercised without TPU hardware. Benchmarks (`bench.py`) run on the real
chip instead.
"""

import os

# Must be set before jax is imported anywhere. Note: the environment may pin
# JAX_PLATFORMS to a hardware plugin (e.g. the axon TPU tunnel) which would
# otherwise win — force-assign AND set the config flag after import.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio (no pytest-asyncio in this image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=60))
        return True
    return None


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


async def wait_for(cond, timeout=5.0, interval=0.05):
    """Poll ``cond()`` until truthy or timeout; returns whether it held."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        if cond():
            return True
        if loop.time() > deadline:
            return False
        await asyncio.sleep(interval)
