"""Ring attention correctness: sequence sharded over sp=8 must match full
single-device causal attention to float tolerance."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh
from dynamo_tpu.parallel.ring import ring_attention


def full_causal_attention(q, k, v, positions, scale):
    qf = q.astype(jnp.float32) * scale
    logits = jnp.einsum("bthd,bshd->bhts", qf, k.astype(jnp.float32))
    mask = positions[:, None, None, :] <= positions[:, None, :, None]
    logits = jnp.where(mask, logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", w, v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("gqa", [False, True])
def test_ring_matches_full_attention(gqa):
    mesh = make_mesh(MeshPlan(sp=8), jax.devices())
    rng = np.random.default_rng(0)
    b, t, h, hd = 2, 64, 4, 16
    hkv = 2 if gqa else h
    q = jnp.asarray(rng.standard_normal((b, t, h, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, t, hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, t, hkv, hd)), jnp.float32)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32), (b, 1))
    scale = hd**-0.5

    out_ring = ring_attention(q, k, v, positions, mesh)
    k_full, v_full = (jnp.repeat(x, h // hkv, axis=2) for x in (k, v)) if gqa else (k, v)
    out_full = full_causal_attention(q, k_full, v_full, positions, scale)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_full), atol=2e-5, rtol=2e-5)


def test_ring_under_jit():
    mesh = make_mesh(MeshPlan(sp=8), jax.devices())
    rng = np.random.default_rng(1)
    b, t, h, hd = 1, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((b, t, h, hd)), jnp.float32)
    k, v = q, q + 1
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32), (b, 1))

    jitted = jax.jit(lambda q, k, v, p: ring_attention(q, k, v, p, mesh))
    out = jitted(q, k, v, positions)
    ref = full_causal_attention(q, k, v, positions, hd**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_mla_matches_paged_mla():
    """MLA ring prefill (absorbed MQA over [latent; rope-key] streams) must
    match the paged MLA formulation on a whole-prompt prefill — the
    DeepSeek long-context sp path (VERDICT r2 item 3)."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    mesh = make_mesh(MeshPlan(sp=8), jax.devices())
    cfg = PRESETS["test-tiny-mla"]
    params = llama.init_params(cfg, 0)
    b, t, page_size = 2, 32, 8
    pages_per_seq = t // page_size
    num_pages = 1 + b * pages_per_seq
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(1, cfg.vocab_size - 1, (b, t)), jnp.int32)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32), (b, 1))
    tables = jnp.asarray(
        1 + np.arange(b * pages_per_seq).reshape(b, pages_per_seq), jnp.int32
    )
    slots = tables[:, :, None] * page_size + jnp.arange(page_size)[None, None, :]
    slots = slots.reshape(b, t)
    last = jnp.full((b,), t - 1, jnp.int32)

    def run(attn_impl):
        k, v = llama.init_kv_cache(cfg, num_pages=num_pages, page_size=page_size)
        logits, k, v = llama.forward(
            params, cfg, tokens, positions, k, v, tables, slots, last,
            attn_impl=attn_impl, mesh=mesh if attn_impl == "ring" else None,
        )
        return np.asarray(logits), np.asarray(k), np.asarray(v)

    ref_logits, ref_k, ref_v = run(None)
    ring_logits, ring_k, ring_v = run("ring")
    np.testing.assert_allclose(ring_logits, ref_logits, atol=2e-4, rtol=2e-4)
    # The latent/rope caches must still be written through for decode.
    np.testing.assert_allclose(ring_k, ref_k, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(ring_v, ref_v, atol=2e-5, rtol=2e-5)
