"""Preprocessor + backend tests: templates, tokenization, incremental
detokenization (multi-byte safety), stop-string jail semantics."""

from typing import Any, AsyncIterator

from dynamo_tpu.backend import Backend, StopStringJail
from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter, extract_sampling, extract_stop
from dynamo_tpu.protocols.common import EngineOutput, FinishReason
from dynamo_tpu.runtime.engine import AsyncEngine, Context, collect
from dynamo_tpu.tokenizer import ByteTokenizer, IncrementalDetokenizer

TOK = ByteTokenizer()


# -- tokenizer ---------------------------------------------------------------


def test_byte_tokenizer_roundtrip():
    s = "héllo wörld → 漢字"
    assert TOK.decode(TOK.encode(s)) == s
    assert TOK.encode("a", add_bos=True)[0] == ByteTokenizer.BOS


def test_incremental_detokenizer_multibyte():
    s = "né漢"
    ids = TOK.encode(s)
    detok = IncrementalDetokenizer(TOK)
    # Push byte-by-byte: partial UTF-8 sequences must be held, never "�".
    out = ""
    for t in ids:
        delta = detok.push([t])
        assert "�" not in delta
        out += delta
    assert out == s


def test_incremental_detokenizer_batch():
    detok = IncrementalDetokenizer(TOK)
    assert detok.push(TOK.encode("hello ")) == "hello "
    assert detok.push(TOK.encode("world")) == "world"


# -- stop-string jail --------------------------------------------------------


def test_jail_no_stops_passthrough():
    j = StopStringJail([])
    assert j.push("anything") == "anything"


def test_jail_holds_partial_prefix():
    j = StopStringJail(["STOP"])
    assert j.push("abcS") == "abc"  # "S" could start "STOP"
    assert j.push("T") == ""  # "ST" still a prefix
    assert j.push("xy") == "STxy"  # disambiguated: release jailed text
    assert j.triggered is None


def test_jail_triggers_and_truncates():
    j = StopStringJail(["<end>"])
    assert j.push("hello <e") == "hello "
    assert j.push("nd> tail") == ""
    assert j.triggered == "<end>"
    assert j.push("more") == ""  # silent after trigger


def test_jail_flush_releases_pending():
    j = StopStringJail(["ZZZ"])
    j.push("abZ")
    assert j.flush() == "Z"


# -- backend operator --------------------------------------------------------


class FakeEngine(AsyncEngine[Any, dict]):
    """Replays scripted EngineOutput dicts; records whether it was cancelled."""

    def __init__(self, texts: list[str], finish: str = "length") -> None:
        self.texts = texts
        self.finish = finish
        self.closed_early = False

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        try:
            n = len(self.texts)
            for i, t in enumerate(self.texts):
                final = i == n - 1
                yield EngineOutput(
                    token_ids=TOK.encode(t),
                    finish_reason=FinishReason(self.finish) if final else None,
                    cumulative_tokens=i + 1,
                    prompt_tokens=3 if final else None,
                ).to_dict()
        finally:
            if not final or self.closed_early:
                self.closed_early = True


async def test_backend_detokenizes_stream():
    eng = FakeEngine(["Hel", "lo ", "wor", "ld"])
    backend = Backend(eng, TOK)
    req = {"token_ids": [1, 2, 3], "sampling": {}, "stop": {}}
    outs = await collect(backend.generate(req, Context()))
    assert "".join(o.text for o in outs) == "Hello world"
    assert outs[-1].finish_reason == FinishReason.LENGTH
    assert outs[-1].prompt_tokens == 3


async def test_backend_stop_string_truncates_and_cancels():
    eng = FakeEngine(["one two ", "<e", "nd> junk", "never seen"])
    backend = Backend(eng, TOK)
    req = {"token_ids": [1], "sampling": {}, "stop": {"stop_strings": ["<end>"]}}
    outs = await collect(backend.generate(req, Context()))
    assert "".join(o.text for o in outs) == "one two "
    assert outs[-1].finish_reason == FinishReason.STOP


# -- preprocessor ------------------------------------------------------------


def test_prompt_formatter_default_template():
    f = PromptFormatter()
    text = f.render([{"role": "user", "content": "hi"}])
    assert "<|im_start|>user\nhi<|im_end|>" in text
    assert text.endswith("<|im_start|>assistant\n")


def test_prompt_formatter_custom_template():
    f = PromptFormatter("{{ bos_token }}{% for m in messages %}[{{ m['role'] }}]{{ m['content'] }}{% endfor %}", bos_token="<s>")
    assert f.render([{"role": "user", "content": "x"}]) == "<s>[user]x"


def test_extract_sampling_and_stop():
    body = {
        "temperature": 0.7, "top_p": 0.9, "seed": 42, "max_tokens": 99,
        "stop": ["\n\n"],
        "nvext": {"top_k": 50, "ignore_eos": True, "min_tokens": 3, "stop_token_ids": [7]},
    }
    s = extract_sampling(body)
    assert (s.temperature, s.top_k, s.top_p, s.seed) == (0.7, 50, 0.9, 42)
    st = extract_stop(body, default_max_tokens=512)
    assert st.max_tokens == 99 and st.stop_strings == ["\n\n"]
    assert st.ignore_eos and st.min_tokens == 3 and st.stop_token_ids == [7]


def test_extract_defaults():
    s = extract_sampling({})
    assert s.temperature == 1.0 and s.top_p == 1.0 and s.top_k == 0
    st = extract_stop({}, default_max_tokens=256)
    assert st.max_tokens == 256 and not st.stop_strings


class EchoEngine(AsyncEngine[Any, dict]):
    def __init__(self):
        self.last_request = None

    async def generate(self, request, context):
        self.last_request = request
        yield request


async def test_preprocessor_forward_edge():
    eng = EchoEngine()
    pre = OpenAIPreprocessor(eng, TOK, default_max_tokens=64)
    body = {"messages": [{"role": "user", "content": "hey"}], "temperature": 0, "model": "m1"}
    [downstream_req] = await collect(pre.generate(body, Context()))
    assert downstream_req["model"] == "m1"
    assert downstream_req["stop"]["max_tokens"] == 64
    text = TOK.decode(downstream_req["token_ids"])
    assert "hey" in text and "assistant" in text


async def test_preprocessor_completions_prompt():
    eng = EchoEngine()
    pre = OpenAIPreprocessor(eng, TOK, add_bos=False)
    [req] = await collect(pre.generate({"prompt": "2+2="}, Context()))
    assert TOK.decode(req["token_ids"]) == "2+2="


async def test_preprocessor_pretokenized_prompt():
    eng = EchoEngine()
    pre = OpenAIPreprocessor(eng, TOK)
    [req] = await collect(pre.generate({"prompt": [5, 6, 7]}, Context()))
    assert req["token_ids"] == [5, 6, 7]


async def test_preprocessor_bad_prompt_type_raises():
    import pytest

    pre = OpenAIPreprocessor(EchoEngine(), TOK)
    with pytest.raises(ValueError):
        await collect(pre.generate({"prompt": ["a", "b"]}, Context()))
