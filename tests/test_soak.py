"""Control-plane soak: a 16-worker mock fleet under sustained load with churn.

Parity: reference `lib/runtime/tests/soak.rs` + mocker-fleet exercises
(SURVEY.md §4). The KV router's world model is the system under test: with
workers dying and joining mid-load, the indexer must (a) drop dead workers'
blocks, (b) admit new workers, and (c) converge to exactly the blocks each
live worker's allocator actually caches.
"""

import asyncio

import pytest

from dynamo_tpu.bench.harness import run_level
from dynamo_tpu.bench.synthesizer import SyntheticConfig, synthesize
from conftest import wait_for
from dynamo_tpu.launch import make_worker_spec, run_local, serve_worker


async def _kill_worker(handles, service) -> int:
    """Simulate a crash: revoke the worker's instance records, stop the engine."""
    wid = service.core.config.worker_id
    store = handles["runtime"].store
    for key in list((await store.get_prefix("instances/")).keys()):
        if key.endswith(f":{wid:x}"):
            await store.delete(key)
    await service.close()
    handles["services"].remove(service)
    return wid


@pytest.mark.slow
@pytest.mark.e2e
async def test_soak_16_worker_fleet_with_churn():
    handles = await run_local(
        "test-tiny", port=0, num_workers=16, router_mode="kv", mock=True,
        num_pages=512, max_batch_size=64,
    )
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        entry = handles["http"].manager.get("test-tiny")
        indexer = entry.aux[0].indexer

        workload = synthesize(SyntheticConfig(
            num_requests=150, shared_prefix_len=32, num_groups=8,
            group_prefix_len=32, unique_len=128, osl_mean=32, seed=11,
        ))

        async def churn() -> tuple[list[int], list[int]]:
            await asyncio.sleep(0.5)  # mid-load
            killed = []
            for victim in list(handles["services"][:3]):
                killed.append(await _kill_worker(handles, victim))
            # Elastic join: two fresh workers enter the live fleet.
            joined = []
            for _ in range(2):
                spec = make_worker_spec("test-tiny", num_pages=512, max_batch_size=64)
                spec.card.router_mode = "kv"
                spec.mock = True
                lease = await handles["runtime"].secondary_lease()
                svc = await serve_worker(handles["runtime"], spec, lease=lease)
                handles["services"].append(svc)
                joined.append(svc.core.config.worker_id)
            return killed, joined

        load_task = asyncio.create_task(
            run_level(base, "test-tiny", workload, concurrency=24)
        )
        churn_task = asyncio.create_task(churn())
        stats = await load_task
        killed, joined = await churn_task

        # The fleet absorbed the churn: the vast majority of requests served.
        assert stats.requests == 150
        assert stats.errors <= 30, stats  # in-flight on 3 killed workers
        assert stats.output_tokens > 0

        # Event-rate soak: the indexer processed a meaningful block volume.
        assert indexer.num_blocks > 100, indexer.num_blocks

        # Dead workers fully evicted from the router's world model.
        assert await wait_for(
            lambda: all(indexer.worker_block_counts().get(w, 0) == 0 for w in killed)
        ), (killed, indexer.worker_block_counts())

        # Consistency: every live worker's index entry equals exactly what
        # its allocator holds (snapshot-on-subscribe covers late joiners).
        def consistent() -> bool:
            counts = indexer.worker_block_counts()
            for svc in handles["services"]:
                wid = svc.core.config.worker_id
                have = len(svc.core.allocator.cache_snapshot().stored)
                if counts.get(wid, 0) != have:
                    return False
            return True

        assert await wait_for(consistent, timeout=15.0), (
            indexer.worker_block_counts(),
            {s.core.config.worker_id: len(s.core.allocator.cache_snapshot().stored)
             for s in handles["services"]},
        )
        # 13 survivors + 2 joiners are all known to the router.
        assert len(handles["services"]) == 15
        live_ids = {s.core.config.worker_id for s in handles["services"]}
        assert set(joined) <= live_ids
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in list(handles["services"]):
            await svc.close()
        await handles["runtime"].close()
