"""Unit tests for the goodput/SLO observability plane (ISSUE 4).

Covers the pieces below the full-stack test in test_observability.py:
the flight-recorder ring (ordering, wrap, filters, JSONL crash dumps),
compile-tracker determinism (one event per bucket, warn-once storms),
EngineCore step/crash records on the mock runner, the P^2 streaming
quantile estimators, SLO accounting, and trace-id log injection.
"""

import json
import logging

import pytest

from dynamo_tpu.config import SloSettings, load_slo_settings
from dynamo_tpu.mocker import build_mock_core
from dynamo_tpu.observability.compile import (
    REASON_NEW_SHAPE,
    REASON_WARM_CACHE,
    CompileTracker,
    timed_dispatch,
)
from dynamo_tpu.observability.flight import CRASH, STEP, FlightRecorder
from dynamo_tpu.observability.slo import (
    SloAccountant,
    StreamingQuantile,
    StreamingQuantiles,
    percentile,
)
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.logging import TraceContextFilter
from dynamo_tpu.tracing import Span


# -- flight recorder ring ----------------------------------------------------


def test_flight_ring_orders_and_wraps():
    ring = FlightRecorder(capacity=4)
    for i in range(10):
        ring.record(STEP, i=i)
    records = ring.snapshot()
    assert len(records) == 4
    # seq is globally monotonic, so a wrap shows as a gap from 0.
    assert [r["seq"] for r in records] == [6, 7, 8, 9]
    assert [r["i"] for r in records] == [6, 7, 8, 9]
    assert all(r["kind"] == STEP and "ts" in r for r in records)


def test_flight_snapshot_filters():
    ring = FlightRecorder(capacity=16)
    for i in range(6):
        ring.record(STEP, i=i)
    ring.record(CRASH, error="Boom")
    assert len(ring.snapshot(kind=CRASH)) == 1
    steps = ring.snapshot(kind=STEP, last=2)
    assert [r["i"] for r in steps] == [4, 5]
    assert len(ring.snapshot(last=3)) == 3
    ring.clear()
    assert len(ring) == 0


def test_flight_dump_jsonl_explicit_path(tmp_path):
    ring = FlightRecorder(capacity=8)
    ring.record(STEP, decode_rows=2)
    ring.record(CRASH, error="RuntimeError", detail="boom")
    path = ring.dump_jsonl(str(tmp_path / "dump.jsonl"), reason="engine_step_failure")
    lines = [json.loads(line) for line in open(path)]
    assert lines[0]["kind"] == "dump_header"
    assert lines[0]["reason"] == "engine_step_failure"
    assert lines[0]["records"] == 2
    assert [l["kind"] for l in lines[1:]] == [STEP, CRASH]
    assert lines[2]["error"] == "RuntimeError"


def test_flight_dump_default_dir_env(tmp_path, monkeypatch):
    monkeypatch.setenv("DYN_FLIGHT_DUMP_DIR", str(tmp_path / "dumps"))
    ring = FlightRecorder(capacity=8)
    ring.record(STEP)
    path = ring.dump_jsonl()
    assert path.startswith(str(tmp_path / "dumps"))
    assert len(open(path).readlines()) == 2  # header + 1 record


def test_flight_capacity_env(monkeypatch):
    monkeypatch.setenv("DYN_FLIGHT_BUFFER", "3")
    ring = FlightRecorder()
    for i in range(5):
        ring.record(STEP, i=i)
    assert len(ring) == 3


# -- compile tracker ---------------------------------------------------------


def test_compile_tracker_one_event_per_bucket():
    sink_events = []
    tracker = CompileTracker(threshold_ms=50.0)
    tracker.bind_sink(lambda kind, **f: sink_events.append((kind, f)))
    key = (8, 16, 4, 0, "reference")

    first = tracker.observe("step", key, 0.2)  # 200 ms: a real compile
    assert first is not None
    assert first["reason"] == REASON_NEW_SHAPE
    assert first["bucket"] == list(key)
    # Re-hit of the same bucket: deterministic zero events, regardless of time.
    for _ in range(5):
        assert tracker.observe("step", key, 0.3) is None
    # Same bucket under a different program is a distinct compile.
    assert tracker.observe("multi_step", key, 0.001)["reason"] == REASON_WARM_CACHE

    assert tracker.counts() == {
        ("step", REASON_NEW_SHAPE): 1,
        ("multi_step", REASON_WARM_CACHE): 1,
    }
    assert tracker.total == 2
    assert len(tracker.events()) == 2
    assert [k for k, _ in sink_events] == ["compile", "compile"]
    # Dispatch time accumulates over every call, not just first executions.
    assert tracker.dispatch_seconds_total == pytest.approx(0.2 + 5 * 0.3 + 0.001)


def test_compile_storm_warns_once(caplog):
    sink_kinds = []
    tracker = CompileTracker(
        threshold_ms=50.0, storm_window=100, storm_threshold=3, warmup_dispatches=0
    )
    tracker.bind_sink(lambda kind, **f: sink_kinds.append(kind))
    with caplog.at_level(logging.WARNING, logger="dynamo_tpu.observability.compile"):
        for i in range(6):  # six slow compiles on six fresh buckets
            tracker.observe("step", (i,), 0.2)
    assert tracker.storm_warned
    assert sink_kinds.count("compile_storm") == 1
    assert sum("recompile storm" in r.message for r in caplog.records) == 1


def test_compile_storm_respects_warmup():
    tracker = CompileTracker(
        threshold_ms=50.0, storm_window=100, storm_threshold=3, warmup_dispatches=32
    )
    for i in range(10):  # the lattice legitimately filling during warm-up
        tracker.observe("step", (i,), 0.2)
    assert not tracker.storm_warned


def test_timed_dispatch_noop_and_exception_paths():
    # None tracker: pure no-op, call sites need no branching.
    with timed_dispatch(None, "step", (1,)):
        pass
    tracker = CompileTracker(threshold_ms=50.0)
    with pytest.raises(ValueError):
        with timed_dispatch(tracker, "step", (1,)):
            raise ValueError("dispatch failed")
    # A failed dispatch is not a first execution: the bucket stays unseen.
    assert tracker.total == 0
    with timed_dispatch(tracker, "step", (1,)):
        pass
    assert tracker.total == 1


# -- EngineCore integration (mock runner) ------------------------------------


def _greedy_req(prompt, max_tokens=4):
    return PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens),
    )


def test_engine_core_records_step_flight():
    core = build_mock_core(realtime=False)
    core.add_request(_greedy_req([1, 2, 3, 4, 5], max_tokens=4))
    core.add_request(_greedy_req([7, 8, 9], max_tokens=4))
    for _ in range(64):
        if not core.has_work:
            break
        core.step()
    records = core.flight.snapshot(kind=STEP)
    assert records, "engine steps produced no flight records"
    seqs = [r["seq"] for r in records]
    assert seqs == sorted(seqs)
    for r in records:
        for key in ("step_kind", "decode_rows", "chunk_rows", "chunk_tokens",
                    "free_pages", "waiting", "running", "wall_ms", "preemptions"):
            assert key in r, r
        assert r["step_kind"] in ("mixed", "prefill", "decode", "drain")
    # The mock fleet prefilled then decoded: both compositions appear.
    kinds = {r["step_kind"] for r in records}
    assert kinds & {"mixed", "prefill"}
    assert "decode" in kinds


def test_engine_core_crash_record_and_dump(tmp_path, monkeypatch):
    core = build_mock_core(realtime=False)
    core.add_request(_greedy_req([1, 2, 3], max_tokens=4))
    core.step()  # one healthy step so the dump has context before the crash

    def boom():
        raise RuntimeError("device array poisoned")

    monkeypatch.setattr(core, "_step_locked", boom)
    with pytest.raises(RuntimeError, match="device array poisoned"):
        core.step()

    crashes = core.flight.snapshot(kind=CRASH)
    assert len(crashes) == 1
    assert crashes[0]["error"] == "RuntimeError"
    assert "device array poisoned" in crashes[0]["detail"]
    assert "free_pages" in crashes[0]

    # The crash dump (what engine/service.py writes on loop death) carries
    # both the healthy context and the crash record.
    path = core.flight.dump_jsonl(str(tmp_path / "crash.jsonl"), reason="engine_step_failure")
    lines = [json.loads(line) for line in open(path)]
    assert lines[0]["reason"] == "engine_step_failure"
    kinds = [l["kind"] for l in lines[1:]]
    assert STEP in kinds and CRASH in kinds
    assert kinds[-1] == CRASH  # ordered: the crash is the last thing recorded


# -- P^2 streaming quantiles -------------------------------------------------


def test_percentile_nearest_rank():
    assert percentile([], 0.5) == 0.0
    assert percentile([3.0], 0.99) == 3.0
    xs = [float(i) for i in range(100)]
    assert percentile(xs, 0.5) == 50.0
    assert percentile(xs, 0.99) == 99.0


def test_streaming_quantile_exact_under_five_samples():
    est = StreamingQuantile(0.5)
    for x in (5.0, 1.0, 3.0):
        est.observe(x)
    assert est.value() == 3.0
    assert StreamingQuantile(0.5).value() == 0.0
    with pytest.raises(ValueError):
        StreamingQuantile(1.0)


def test_streaming_quantile_tracks_known_distribution():
    import random

    rng = random.Random(42)
    xs = [rng.random() for _ in range(10000)]
    bundle = StreamingQuantiles()
    for x in xs:
        bundle.observe(x)
    xs.sort()
    for q in (0.5, 0.95, 0.99):
        exact = percentile(xs, q)
        assert bundle.get(q) == pytest.approx(exact, abs=0.02), q
    assert bundle.count == 10000
    snap = bundle.snapshot()
    assert set(snap) == {0.5, 0.95, 0.99}
    assert snap[0.5] <= snap[0.95] <= snap[0.99]


def test_streaming_quantile_shifted_distribution():
    # The fixed-bucket failure mode: all mass near the 500 ms SLO boundary.
    est = StreamingQuantile(0.5)
    for i in range(1000):
        est.observe(0.49 + (i % 100) * 0.0002)  # 490..510 ms
    assert 0.49 <= est.value() <= 0.51


# -- SLO accounting ----------------------------------------------------------


def test_slo_accountant_goodput_ledger():
    acct = SloAccountant(SloSettings(ttft_ms=100.0, itl_p99_ms=20.0))
    # Attains: fast TTFT, tight gaps.
    v = acct.account(ttft_s=0.05, itl_gaps=[0.01] * 5, output_tokens=10, ok=True)
    assert v.met and v.ttft_ok and v.itl_ok
    # TTFT blown: tokens counted, goodput not.
    v = acct.account(ttft_s=0.2, itl_gaps=[0.01], output_tokens=20, ok=True)
    assert not v.met and not v.ttft_ok and v.itl_ok
    # ITL p99 blown.
    v = acct.account(ttft_s=0.05, itl_gaps=[0.01] * 9 + [0.5], output_tokens=5, ok=True)
    assert not v.met and v.ttft_ok and not v.itl_ok
    # Fast but failed: never goodput.
    acct.account(ttft_s=0.01, itl_gaps=[], output_tokens=7, ok=False)
    assert acct.output_tokens_total == 42
    assert acct.goodput_tokens_total == 10
    assert acct.attainment() == pytest.approx(0.25)
    snap = acct.snapshot()
    assert snap["goodput_tokens_total"] == 10
    assert snap["output_tokens_total"] == 42
    assert snap["targets"] == {"ttft_ms": 100.0, "itl_p99_ms": 20.0}


def test_slo_accountant_vacuous_itl_and_empty_state():
    acct = SloAccountant(SloSettings(ttft_ms=100.0, itl_p99_ms=20.0))
    assert acct.attainment() == 1.0  # no requests yet: vacuously attaining
    # A 1-token response has no gaps; its ITL attains by definition.
    assert acct.classify(0.05, []).met


def test_slo_settings_env_override(monkeypatch):
    assert load_slo_settings().ttft_ms == 500.0  # north-star default
    monkeypatch.setenv("DYN_SLO_TTFT_MS", "250")
    monkeypatch.setenv("DYN_SLO_ITL_P99_MS", "25")
    settings = load_slo_settings()
    assert settings.ttft_ms == 250.0
    assert settings.itl_p99_ms == 25.0


# -- trace-id log injection --------------------------------------------------


def _make_record():
    return logging.LogRecord("t", logging.INFO, __file__, 1, "msg", (), None)


def test_trace_context_filter_stamps_active_span():
    f = TraceContextFilter()
    outside = _make_record()
    assert f.filter(outside) is True
    assert not hasattr(outside, "trace_id")  # no span open: record untouched
    with Span("frontend.request") as span:
        inside = _make_record()
        assert f.filter(inside) is True
        assert inside.trace_id == span.trace_id
        assert inside.span_id == span.span_id
    after = _make_record()
    f.filter(after)
    assert not hasattr(after, "trace_id")


def test_trace_context_filter_keeps_explicit_trace_id():
    f = TraceContextFilter()
    with Span("frontend.request"):
        rec = _make_record()
        rec.trace_id = "explicit"
        f.filter(rec)
        assert rec.trace_id == "explicit"
