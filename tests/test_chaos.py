"""Chaos suite: deterministic fault injection + end-to-end failure recovery.

Every scenario arms the process-wide fault plane (`runtime/faults.py`) and
asserts the *recovery* behavior, not just the failure: watch loops
reconnect, circuit breakers open and route around, corrupt KV chunks are
retried, failed prefill tasks requeue to a peer, and a mid-stream engine
death surfaces as a structured SSE error — never a traceback.
docs/ROBUSTNESS.md documents the grammar and semantics;
tools/check_fault_points.py fails this suite if any registered fault point
is never armed here.
"""

import asyncio
import json
import pathlib
import sys

import aiohttp
import numpy as np
import pytest

from dynamo_tpu.runtime.faults import (
    FAULT_POINTS,
    FAULTS,
    CrashFault,
    DropFault,
    FaultRegistry,
    corrupt_bytes,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fault_hygiene():
    """Every test starts and ends with the fault plane disarmed — a leaked
    plan would fail unrelated tests in ways that are miserable to debug."""
    FAULTS.disarm()
    yield
    FAULTS.disarm()


# -- the plane itself --------------------------------------------------------


def test_fault_grammar():
    reg = FaultRegistry()
    reg.arm("tcp.connect:drop@2,engine.step:crash@3+,kv.chunk.send:corrupt@0.5,store.op:delay")
    assert reg.armed
    assert set(reg.counts()) == {"tcp.connect", "engine.step", "kv.chunk.send", "store.op"}
    # @2: only the second call fires.
    assert reg.fire("tcp.connect") is None
    with pytest.raises(DropFault):
        reg.fire("tcp.connect")
    assert reg.fire("tcp.connect") is None
    assert reg.fired("tcp.connect") == 1
    # @3+: every call from the third.
    assert reg.fire("engine.step") is None
    assert reg.fire("engine.step") is None
    for _ in range(3):
        with pytest.raises(CrashFault):
            reg.fire("engine.step")
    # Unarmed point: never fires.
    assert reg.fire("lease.keepalive") is None
    reg.disarm()
    assert not reg.armed and reg.fire("tcp.connect") is None


def test_fault_grammar_rejects_garbage():
    reg = FaultRegistry()
    with pytest.raises(ValueError, match="unknown fault point"):
        reg.arm("tcp.conncet:drop")  # typo fails loudly at arm time
    with pytest.raises(ValueError, match="unknown fault action"):
        reg.arm("tcp.connect:explode")
    with pytest.raises(ValueError, match="probability"):
        reg.arm("tcp.connect:drop@1.5")
    with pytest.raises(ValueError, match="1-based"):
        reg.arm("tcp.connect:drop@0")


def test_probabilistic_fault_is_deterministic_per_seed():
    def firing_pattern(seed):
        reg = FaultRegistry()
        reg.arm("tcp.read:drop@0.3", seed=seed)
        out = []
        for _ in range(50):
            try:
                reg.fire("tcp.read")
                out.append(0)
            except DropFault:
                out.append(1)
        return out

    a, b = firing_pattern(7), firing_pattern(7)
    assert a == b and 0 < sum(a) < 50  # same seed, same sequence; actually fires
    assert firing_pattern(8) != a  # different seed, different sequence


def test_corrupt_bytes_flips_and_preserves_length():
    buf = b"\x00\x01\x02"
    assert corrupt_bytes(buf) == b"\xff\x01\x02"
    assert corrupt_bytes(b"") == b""


async def test_unarmed_plane_is_one_attribute_check(monkeypatch):
    """DYN_FAULTS unset -> FAULTS.armed is False and no call site ever
    reaches fire(): a request flows through TCP transport, store watch, and
    the engine loop with fire() booby-trapped."""
    from dynamo_tpu.mocker import build_mock_service
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.discovery import MemoryStore
    from dynamo_tpu.runtime.engine import Context, collect
    from dynamo_tpu.runtime.tcp import TcpTransport

    assert FAULTS.armed is False

    def boom(point):
        raise AssertionError(f"fire({point!r}) called while disarmed")

    monkeypatch.setattr(FAULTS, "fire", boom)
    svc = await build_mock_service()
    try:
        req = PreprocessedRequest(
            token_ids=[1, 2, 3], sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=3),
        )
        outs = [o async for o in svc.generate(req.to_dict(), Context())]
        assert outs[-1]["finish_reason"] == "length"
    finally:
        await svc.close()
    rt = DistributedRuntime(MemoryStore(), TcpTransport())
    try:
        ep = rt.namespace("ns").component("c").endpoint("e")
        await ep.serve(_Tagged("w"))
        client = ep.client()
        await client.wait_for_instances(count=1, timeout=5)
        items = await collect(client.generate({"q": 1}))
        assert items[0]["tag"] == "w"
    finally:
        await rt.close()


def test_fault_point_coverage():
    """Invokes the tools/ coverage gate (every registered point armed here)."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
    try:
        import check_fault_points
    finally:
        sys.path.pop(0)
    assert check_fault_points.registered_points() == sorted(FAULT_POINTS)
    assert check_fault_points.uncovered_points() == []
    assert check_fault_points.main() == 0
    # A point absent from a hypothetical suite is reported.
    assert check_fault_points.uncovered_points("nothing armed") == sorted(FAULT_POINTS)


# -- circuit breaker ---------------------------------------------------------


def test_breaker_state_machine():
    from dynamo_tpu.runtime.client import (
        BREAKER_CLOSED,
        BREAKER_HALF_OPEN,
        BREAKER_OPEN,
        CircuitBreaker,
    )

    b = CircuitBreaker(threshold=2, open_seconds=1.0)
    assert b.state == BREAKER_CLOSED and b.allow(100.0)
    b.record_failure(100.0)
    assert b.state == BREAKER_CLOSED  # below threshold: still routable
    b.record_failure(100.1)
    assert b.state == BREAKER_OPEN and not b.allow(100.5)
    assert b.allow(101.2)  # open window elapsed: probe admissible
    b.begin_attempt(101.2)
    assert b.state == BREAKER_HALF_OPEN
    assert not b.allow(101.3)  # one probe at a time
    b.record_failure(101.3)  # probe failed: reopen from now
    assert b.state == BREAKER_OPEN and not b.allow(102.0) and b.allow(102.4)
    b.begin_attempt(102.4)
    b.record_success()
    assert b.state == BREAKER_CLOSED and b.failures == 0 and b.allow(102.4)
    # Interleaved success resets the consecutive-failure count.
    b.record_failure(103.0)
    b.record_success()
    b.record_failure(103.1)
    assert b.state == BREAKER_CLOSED


class _Tagged:
    """Minimal AsyncEngine for routing tests."""

    def __init__(self, tag):
        self.tag = tag
        self.calls = 0

    async def generate(self, request, context):
        self.calls += 1
        yield {"tag": self.tag, "echo": request}


async def test_direct_mode_no_instances_error_carries_context():
    from dynamo_tpu.runtime.client import NoInstancesError
    from dynamo_tpu.runtime.component import DistributedRuntime

    rt = DistributedRuntime.detached()
    try:
        ep = rt.namespace("ns").component("c").endpoint("e")
        inst = await ep.serve(_Tagged("w"))
        client = ep.client(router_mode="direct")
        await client.wait_for_instances(count=1, timeout=5)
        with pytest.raises(NoInstancesError) as exc_info:
            async for _ in client.generate({}, instance_id=0xDEAD):
                pass
        assert exc_info.value.endpoint_path == ep.path
        assert exc_info.value.known_instances == 1
        # Direct mode respects the breaker: enough recorded failures make
        # even a live pinned instance unroutable.
        for _ in range(client._breaker_threshold):
            client.inhibit(inst.instance_id)
        with pytest.raises(NoInstancesError, match="breaker open"):
            client._pick(inst.instance_id)
    finally:
        await rt.close()


async def test_draining_instance_is_ineligible():
    """A worker announcing metadata.draining=True stops receiving new
    requests while its record (and in-flight streams) stay alive."""
    import dataclasses

    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.discovery import MemoryStore

    store = MemoryStore()
    rt1 = DistributedRuntime(store)
    rt2 = DistributedRuntime(store, rt1.transport)
    try:
        e1, e2 = _Tagged("a"), _Tagged("b")
        i1 = await rt1.namespace("ns").component("c").endpoint("e").serve(e1)
        await rt2.namespace("ns").component("c").endpoint("e").serve(e2)
        client = rt1.namespace("ns").component("c").endpoint("e").client()
        await client.wait_for_instances(count=2, timeout=5)
        draining = dataclasses.replace(i1, metadata={**i1.metadata, "draining": True})
        await store.put(i1.key, draining.to_bytes(), lease_id=i1.instance_id)
        from conftest import wait_for

        assert await wait_for(
            lambda: bool(client._instances.get(i1.instance_id, i1).metadata.get("draining"))
        )
        for _ in range(6):
            async for item in client.generate({}):
                assert item["tag"] == "b"
    finally:
        await rt1.close()
        await rt2.close()


# -- watch-loop resilience ---------------------------------------------------


async def test_watch_loop_restarts_after_store_watch_death():
    """satellite (a): a dying instance watch reconnects (counted + warned)
    instead of leaving the client frozen on a stale table forever."""
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.discovery import MemoryStore

    store = MemoryStore()
    rt = DistributedRuntime(store)
    try:
        ep = rt.namespace("ns").component("c").endpoint("e")
        await ep.serve(_Tagged("a"))
        FAULTS.arm("store.watch:crash@1")  # kills the first event delivery
        client = ep.client()
        await client.start()
        from conftest import wait_for

        assert await wait_for(lambda: client.watch_restarts >= 1, timeout=10)
        # The restarted watch is live: a new instance becomes visible.
        rt2 = DistributedRuntime(store, rt.transport)
        await rt2.namespace("ns").component("c").endpoint("e").serve(_Tagged("b"))
        assert await wait_for(lambda: len(client.instances()) == 2, timeout=10)
        assert client.watch_staleness() == 0.0  # healthy again
        from dynamo_tpu.runtime.client import watch_snapshot

        assert watch_snapshot()[ep.path]["restarts"] >= 1
        await rt2.close()
    finally:
        await rt.close()


# -- store / lease / tcp drills ---------------------------------------------


async def test_store_op_fault_drill():
    from dynamo_tpu.runtime.store_server import StoreClient, StoreServer

    server = await StoreServer(host="127.0.0.1", port=0).start()
    client = StoreClient("127.0.0.1", server.port)
    try:
        await client.put("k", b"v")
        FAULTS.arm("store.op:drop@1")
        with pytest.raises(ConnectionError):
            await client.get("k")
        assert await client.get("k") == b"v"  # next op unaffected
        assert FAULTS.fired("store.op") == 1
    finally:
        await client.close()
        await server.close()


async def test_store_replicate_fault_drill():
    """Chaos on the replication stream: a corrupt record forces a follower
    desync + full resync, a dropped stream forces a reconnect — either way
    the stores reconverge byte-identically, never silently diverge."""
    from test_store_ha import _cluster, _converged, _shutdown, _wait

    from dynamo_tpu.runtime.store_server import StoreClient

    peers, servers, coords = await _cluster(2, promote_after_s=30, poll_s=0.05)
    client = StoreClient.from_url(",".join(peers))
    try:
        await client.put("cfg/base", b"v0")
        await _wait(lambda: coords[1].seq == coords[0].seq, msg="initial catch-up")

        FAULTS.arm("store.replicate:corrupt@1")  # next applied record is garbage
        await client.put("cfg/a", b"v1")
        await _wait(lambda: coords[1].seq == coords[0].seq, msg="resync after corrupt")
        assert FAULTS.fired("store.replicate") == 1
        assert await _converged(servers[0], servers[1])

        FAULTS.arm("store.replicate:drop@1")  # stream dies mid-flight
        await client.put("cfg/b", b"v2")
        await _wait(lambda: coords[1].seq == coords[0].seq, msg="reconnect after drop")
        assert FAULTS.fired("store.replicate") == 1
        assert await _converged(servers[0], servers[1])
        assert coords[1].role == "follower"  # recovery never usurped the leader
    finally:
        await _shutdown(servers, client)


async def test_store_promote_fault_drill():
    """A crash mid-promotion aborts it cleanly (no epoch bump, no role
    change); a later poll retries and exactly one leader emerges — the drill
    that proves there are never two."""
    from test_store_ha import _cluster, _shutdown, _wait

    from dynamo_tpu.runtime.store_server import StoreClient

    peers, servers, coords = await _cluster(3, promote_after_s=0.2, poll_s=0.05)
    client = StoreClient.from_url(",".join(peers))
    try:
        await client.put("cfg/a", b"1")
        await _wait(
            lambda: coords[1].seq == coords[0].seq and coords[2].seq == coords[0].seq,
            msg="followers caught up",
        )
        FAULTS.arm("store.promote:crash@1")  # first promotion attempt dies
        await servers[0].close()
        await _wait(
            lambda: any(c.role == "leader" for c in coords[1:]),
            msg="promotion despite the crashed first attempt",
        )
        assert FAULTS.fired("store.promote") == 1
        assert [c.role for c in coords[1:]].count("leader") == 1
        # The aborted attempt left no trace: one epoch bump total.
        assert max(c.epoch for c in coords[1:]) == 2
        assert await client.get("cfg/a") == b"1"
    finally:
        await _shutdown(servers, client)


async def test_lease_keepalive_fault_drill():
    from dynamo_tpu.runtime.discovery import MemoryStore

    store = MemoryStore()
    lease = await store.create_lease(5.0)
    FAULTS.arm("lease.keepalive:drop@1")
    with pytest.raises(ConnectionError):
        await store.keep_alive(lease.id)
    await store.keep_alive(lease.id)  # refresh path recovers


async def test_tcp_faults_are_retried_transparently():
    """Caller-side connect/write/read drops are absorbed by the client's
    cross-replica retry (here: same instance, second attempt) — the request
    still completes and the breaker stays below threshold."""
    from dynamo_tpu.runtime.client import BREAKER_CLOSED
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.discovery import MemoryStore
    from dynamo_tpu.runtime.engine import collect
    from dynamo_tpu.runtime.tcp import TcpTransport

    rt = DistributedRuntime(MemoryStore(), TcpTransport())
    try:
        ep = rt.namespace("ns").component("c").endpoint("e")
        engine = _Tagged("w")
        inst = await ep.serve(engine)
        for point in ("tcp.connect", "tcp.write", "tcp.read"):
            client = ep.client()
            await client.wait_for_instances(count=1, timeout=5)
            FAULTS.arm(f"{point}:drop@1")
            items = await collect(client.generate({"p": point}))
            assert items[0]["tag"] == "w", point
            assert FAULTS.fired(point) == 1, point
            assert client.breaker_states()[inst.instance_id] == BREAKER_CLOSED
            FAULTS.disarm()
    finally:
        await rt.close()


# -- engine service ----------------------------------------------------------


def _req(max_tokens=5):
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

    return PreprocessedRequest(
        token_ids=[1, 2, 3], sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens),
    ).to_dict()


async def test_engine_step_crash_fails_streams_and_recovers():
    """An injected step crash fails in-flight streams with a terminal error
    item, records the crash in the flight ring, and the loop keeps serving."""
    from dynamo_tpu.mocker import build_mock_service
    from dynamo_tpu.observability.flight import CRASH
    from dynamo_tpu.runtime.engine import Context

    svc = await build_mock_service()
    try:
        FAULTS.arm("engine.step:crash@1")
        outs = [o async for o in svc.generate(_req(), Context())]
        assert outs[-1]["finish_reason"] == "error"
        crashes = svc.core.flight.snapshot(kind=CRASH)
        assert any(c.get("where") == "engine_loop" and c.get("error") == "CrashFault" for c in crashes)
        # The fault is spent (@1): the very next request completes normally.
        outs = [o async for o in svc.generate(_req(), Context())]
        assert outs[-1]["finish_reason"] == "length"
        assert sum(len(o["token_ids"]) for o in outs) == 5
    finally:
        await svc.close()


def test_engine_step_crash_with_lookahead_inflight_drains_cleanly():
    """satellite (ISSUE 11): a crash inside ``engine.step`` while a chained
    lookahead is in flight must drain the whole pipeline — the in-flight
    handle is aborted (its rows counted in the CRASH flight record), every
    page returns to the allocator, and the engine serves fresh work on the
    next request."""
    from dynamo_tpu.engine.core import EngineConfig
    from dynamo_tpu.mocker import build_mock_core
    from dynamo_tpu.observability.flight import CRASH
    from dynamo_tpu.protocols.common import (
        FinishReason, PreprocessedRequest, SamplingOptions, StopConditions,
    )

    core = build_mock_core(EngineConfig(
        num_pages=128, page_size=16, max_batch_size=8, max_seq_len=512,
        chunk_prefill_tokens=64, overlap=True, enable_prefix_caching=False,
    ), realtime=False)

    def req():
        return PreprocessedRequest(
            token_ids=list(range(1, 25)), sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=16, ignore_eos=True),
        )

    seqs = [core.add_request(req()) for _ in range(3)]
    for _ in range(6):  # prime the pipeline: fill step, then chained steps
        core.step()
        if core._inflight is not None and core.overlap_step_counts.get("overlapped"):
            break
    assert core._inflight is not None, "no lookahead in flight"
    inflight_rows = len(core._inflight.batch)

    orig = core.runner.step_async

    def boom(*a, **k):
        raise CrashFault("engine.step")

    core.runner.step_async = boom
    try:
        with pytest.raises(CrashFault):
            core.step()
    finally:
        core.runner.step_async = orig

    # The abort drained the in-flight handle and the freshly built batch:
    # nothing queued, nothing leaked, the crash record counts the rows.
    crashes = core.flight.snapshot(kind=CRASH)
    assert crashes, "step crash left no flight record"
    rec = crashes[-1]
    assert rec["error"] == "CrashFault"
    assert rec["inflight_rows"] >= inflight_rows > 0
    assert core._inflight is None
    assert not core.has_work
    assert core.allocator.stats().active_pages == 0  # no leaked pages
    assert all(s.finish_reason is FinishReason.ERROR for s in seqs)

    # Recovery: the very next request completes normally.
    fresh = core.add_request(req())
    for _ in range(64):
        if not core.has_work:
            break
        core.step()
    assert fresh.finish_reason is FinishReason.LENGTH
    assert fresh.num_generated == 16
    assert core.allocator.stats().active_pages == 0


def test_sched_admit_fault_drill():
    """satellite (c, ISSUE 9): a drop injected at the admission seam
    (``sched.admit``) cancels exactly the request being admitted — its
    stream terminates with CANCELLED instead of hanging outside every
    queue — and the next step's admission proceeds normally."""
    from dynamo_tpu.mocker import build_mock_core
    from dynamo_tpu.protocols.common import (
        FinishReason, PreprocessedRequest, SamplingOptions, StopConditions,
    )

    core = build_mock_core(realtime=False)

    def req():
        return PreprocessedRequest(
            token_ids=[1, 2, 3, 4], sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
        )

    FAULTS.arm("sched.admit:drop@1")
    a = core.add_request(req())
    b = core.add_request(req())
    results: dict[int, object] = {}
    for _ in range(64):
        if not core.has_work:
            break
        for seq, out in core.step():
            if out.finish_reason is not None:
                results[seq.seq_id] = out.finish_reason
    assert not core.has_work
    assert FAULTS.fired("sched.admit") == 1
    # The head request at the faulted admission was killed and reaped...
    assert results[a.seq_id] is FinishReason.CANCELLED
    assert a.finish_reason is FinishReason.CANCELLED
    # ...while the second request rode the recovered admission path.
    assert results[b.seq_id] is FinishReason.LENGTH
    assert b.num_generated == 4


def test_sched_admit_delay_defers_without_loss():
    """``sched.admit:delay`` only postpones admission: every request still
    completes (the deferred head is retried on the next step)."""
    from dynamo_tpu.mocker import build_mock_core
    from dynamo_tpu.protocols.common import (
        FinishReason, PreprocessedRequest, SamplingOptions, StopConditions,
    )

    core = build_mock_core(realtime=False)
    FAULTS.arm("sched.admit:delay@1")
    seqs = [
        core.add_request(PreprocessedRequest(
            token_ids=[5, 6, 7], sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=3, ignore_eos=True),
        ))
        for _ in range(2)
    ]
    for _ in range(64):
        if not core.has_work:
            break
        core.step()
    assert not core.has_work
    assert FAULTS.fired("sched.admit") == 1
    assert all(s.finish_reason is FinishReason.LENGTH for s in seqs)


async def test_intake_drain_on_dead_loop_fails_queued_requests():
    """satellite (c): a request queued at intake but never admitted gets a
    terminal error item (not a hang) and the flight ring records the drain."""
    import time as time_mod

    from dynamo_tpu.engine.service import _SENTINEL, JaxEngineService
    from dynamo_tpu.mocker import build_mock_core
    from dynamo_tpu.observability.flight import CRASH
    from dynamo_tpu.protocols.common import FinishReason
    from dynamo_tpu.runtime.engine import Context

    svc = JaxEngineService(build_mock_core())  # loop never started: dead engine
    out_q = asyncio.Queue()
    svc._intake.put_nowait((_req(), Context(), out_q, time_mod.perf_counter()))
    await svc.close()
    item = out_q.get_nowait()
    assert item.finish_reason is FinishReason.ERROR
    assert out_q.get_nowait() is _SENTINEL  # consumer unblocks, no hang
    crashes = svc.core.flight.snapshot(kind=CRASH)
    assert any(c.get("where") == "intake_drain" and c.get("drained") == 1 for c in crashes)


async def test_engine_drain_finishes_inflight_then_refuses():
    from dynamo_tpu.mocker import build_mock_service
    from dynamo_tpu.runtime.engine import Context

    svc = await build_mock_service()
    try:
        stream_task = asyncio.create_task(
            _collect_tokens(svc, _req(max_tokens=20))
        )
        await asyncio.sleep(0.05)  # let it get admitted
        drained = await svc.drain(timeout=30.0)
        assert drained is True
        assert len(await stream_task) == 20  # in-flight work finished intact
        with pytest.raises(RuntimeError, match="draining"):
            async for _ in svc.generate(_req(), Context()):
                pass
    finally:
        await svc.close()


async def _collect_tokens(svc, req):
    from dynamo_tpu.runtime.engine import Context

    return [t async for o in svc.generate(req, Context()) for t in o["token_ids"]]


# -- KV wire integrity -------------------------------------------------------


async def test_kv_chunk_send_corruption_detected_and_retried():
    """kv.chunk.send:corrupt@1 mangles the first wire chunk; the receiver's
    crc check rejects it without touching session state, the sender retries
    that chunk once from its clean copy, and the stream completes
    byte-identical with zero rollbacks."""
    from dynamo_tpu.disagg.transfer import KvTransferService, send_blocks_chunked
    from dynamo_tpu.runtime.transport import InMemoryTransport
    from dynamo_tpu.tokens import compute_block_hashes
    from tests.test_transfer_pipeline import PAGE, _commit_chain, _core

    src, dst = _core(), _core()
    hashes = compute_block_hashes(list(range(5 * PAGE)), PAGE, salt=0)
    payloads = _commit_chain(src, hashes)
    transport = InMemoryTransport()
    svc = KvTransferService(dst)
    await transport.register_engine("kv", svc)

    FAULTS.arm("kv.chunk.send:corrupt@1")
    out = await send_blocks_chunked(transport, "mem://kv", "r1", src, hashes, chunk_pages=2)
    assert out["injected"] == 5 and out["crc_retries"] == 1
    assert svc.crc_failures == 1 and svc.rollbacks == 0
    pids = dst.allocator.match_prefix(hashes)
    assert len(pids) == 5
    for pid, h in zip(pids, hashes):
        k_got, v_got = dst.runner.read_page(pid)
        np.testing.assert_array_equal(k_got, payloads[h][0])
        np.testing.assert_array_equal(v_got, payloads[h][1])
    dst.allocator.release(pids)


async def test_kv_chunk_recv_drop_rolls_back_stream():
    """A receiver-side failure mid-stream rolls the session back: pins are
    released and the decode worker is left with at most a valid, evictable
    chain prefix — never a pinned or inconsistent partial transfer."""
    from dynamo_tpu.disagg.transfer import KvTransferService, send_blocks_chunked
    from dynamo_tpu.runtime.transport import InMemoryTransport
    from dynamo_tpu.tokens import compute_block_hashes
    from tests.test_transfer_pipeline import PAGE, _commit_chain, _core

    src, dst = _core(), _core()
    hashes = compute_block_hashes(list(range(5 * PAGE)), PAGE, salt=0)
    _commit_chain(src, hashes)
    transport = InMemoryTransport()
    svc = KvTransferService(dst)
    await transport.register_engine("kv", svc)

    FAULTS.arm("kv.chunk.recv:drop@2")  # chunk 1 lands, chunk 2 dies
    with pytest.raises(Exception):
        await send_blocks_chunked(transport, "mem://kv", "r1", src, hashes, chunk_pages=2)
    assert svc.rollbacks == 1
    # Rollback drops the session and its pins. The chain-consistent prefix
    # the first chunk already committed stays as ordinary evictable cache
    # (it is valid KV), but the full chain never materializes and nothing
    # is left pinned.
    committed = dst.allocator.match_prefix(hashes)
    assert len(committed) < 5
    dst.allocator.release(committed)
    assert svc.stats()["streams_in_flight"] == 0


async def test_v1_crc_mismatch_truncates_chain():
    """The monolithic (v1) path has no retry channel: a corrupt block
    truncates the chain at the first bad block, keeping every committed
    prefix valid."""
    from dynamo_tpu.disagg.transfer import KvTransferService, send_blocks
    from dynamo_tpu.runtime.transport import InMemoryTransport
    from dynamo_tpu.tokens import compute_block_hashes
    from tests.test_transfer_pipeline import PAGE, _core, _zero_blocks

    dst = _core()
    svc = KvTransferService(dst)
    transport = InMemoryTransport()
    await transport.register_engine("kv", svc)
    hashes = compute_block_hashes(list(range(3 * PAGE)), PAGE, salt=0)
    blocks = _zero_blocks(hashes)
    blocks[1]["k"] = corrupt_bytes(blocks[1]["k"])
    out = await send_blocks(transport, "mem://kv", "r1", blocks)
    assert out["injected"] == 1  # blocks after (and including) the bad one dropped
    assert svc.crc_failures == 1
    assert len(dst.allocator.match_prefix(hashes)) == 1


# -- prefill queue redelivery ------------------------------------------------


async def test_queue_release_counts_requeue_on_peer():
    from dynamo_tpu.disagg.queue import DistributedQueue
    from dynamo_tpu.runtime.component import DistributedRuntime

    rt = DistributedRuntime.detached()
    try:
        q1 = DistributedQueue(rt, "t")
        await q1.put({"job": "a"})
        key, _ = await q1.claim(timeout=2)
        assert q1.requeues == 0  # first delivery is not a requeue
        await q1.release(key)
        rt2 = DistributedRuntime(rt.store, rt.transport)
        q2 = DistributedQueue(rt2, "t")
        rekey, item = await q2.claim(timeout=2)
        assert rekey == key and item["job"] == "a"
        assert q2.requeues == 1  # the peer knows it got a redelivery
        await q2.delete(rekey)
        # Ack cleans the delivered marker: a fresh task under the same name
        # is not miscounted.
        await q1.put({"job": "b"})
        k2, _ = await q1.claim(timeout=2)
        assert q1.requeues == 0
        await q1.delete(k2)
        await rt2.close()
    finally:
        await rt.close()


async def test_queue_lease_expiry_counts_requeue():
    from dynamo_tpu.disagg.queue import DistributedQueue
    from dynamo_tpu.runtime.component import DistributedRuntime

    rt = DistributedRuntime.detached()
    try:
        producer = DistributedQueue(rt, "t")
        await producer.put({"job": "a"})
        claimant_rt = DistributedRuntime(rt.store, rt.transport, lease_ttl=0.3)
        cq = DistributedQueue(claimant_rt, "t")
        await cq.claim(timeout=2)
        claimant_rt._keepalive_task.cancel()  # claimant dies
        await asyncio.sleep(0.8)
        reclaimed = await producer.claim(timeout=5)
        assert reclaimed is not None and reclaimed[1]["job"] == "a"
        assert producer.requeues == 1
    finally:
        await rt.close()


@pytest.mark.e2e
async def test_prefill_crash_requeues_to_peer_before_local_fallback():
    """prefill.exec:crash@1 kills the first worker's attempt; the claim is
    released, a peer reclaims and completes it, and the decode side never
    falls back to local prefill."""
    from dynamo_tpu.disagg.router import DisaggConfig
    from dynamo_tpu.launch import run_local

    disagg = DisaggConfig(max_local_prefill_length=24, min_remote_prefill_blocks=1)
    handles = await run_local(
        "test-tiny", port=0, num_workers=1, num_prefill_workers=2,
        disagg=disagg, num_pages=64, max_batch_size=8,
    )
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        FAULTS.arm("prefill.exec:crash@1")
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "prompt": "r" * 48, "max_tokens": 4, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
        assert out["usage"]["prompt_tokens_details"]["cached_tokens"] >= 32
        assert FAULTS.fired("prefill.exec") == 1
        operator = handles["services"][0].disagg_operator
        assert operator.remote_prefills == 1 and operator.local_prefills == 0
        workers = [
            svc.prefill_worker for svc in handles["services"]
            if getattr(svc, "prefill_worker", None) is not None
        ]
        assert len(workers) == 2
        assert sum(w.queue.requeues for w in workers) == 1  # peer saw a redelivery
        assert sum(w.completed for w in workers) == 1
    finally:
        FAULTS.disarm()
        from tests.conftest import stop_stack

        await stop_stack(handles)


# -- end-to-end: mid-stream death, breaker, drain ----------------------------


@pytest.mark.e2e
async def test_midstream_crash_sse_error_breaker_and_failover(monkeypatch):
    """The flagship scenario: an engine dies mid-SSE-stream -> the client
    gets a structured OpenAI-style error event (no traceback) and [DONE];
    then that worker's engine is killed outright -> its breaker opens and
    follow-up requests succeed on the surviving replica."""
    monkeypatch.setenv("DYN_CLIENT_BREAKER_THRESHOLD", "1")
    from tests.conftest import start_stack, stop_stack

    handles, base = await start_stack(num_workers=2)
    try:
        async with aiohttp.ClientSession() as s:
            # Warm up: both replicas serve.
            body = {"model": "test-tiny", "prompt": "warm", "max_tokens": 2, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200

            FAULTS.arm("engine.step:crash@3")
            stream_body = {
                "model": "test-tiny", "prompt": "stream me", "max_tokens": 16,
                "temperature": 0, "stream": True,
            }
            events, done = [], False
            async with s.post(base + "/v1/completions", json=stream_body) as r:
                assert r.status == 200  # headers were already out: stays 200
                raw = await r.text()
            for line in raw.splitlines():
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    done = True
                    break
                events.append(json.loads(payload))
            assert done  # the stream closed cleanly, not mid-frame
            errors = [e for e in events if "error" in e]
            assert len(errors) == 1
            assert errors[0]["error"]["code"] == "mid_stream_failure"
            assert errors[0]["error"]["type"] == "engine_error"
            assert "Traceback" not in raw and "CrashFault" not in raw
            FAULTS.disarm()

            # Kill one worker's engine outright: requests that land on it
            # fail pre-stream, its breaker opens (threshold 1), and every
            # follow-up completes on the surviving replica.
            await handles["services"][0].close()
            for _ in range(4):
                async with s.post(base + "/v1/completions", json=body) as r:
                    assert r.status == 200, await r.text()

            from dynamo_tpu.runtime.client import BREAKER_OPEN, breaker_snapshot

            assert BREAKER_OPEN in breaker_snapshot().values()
            async with s.get(base + "/metrics") as r:
                metrics_text = await r.text()
            assert "dynamo_client_breaker_state" in metrics_text
    finally:
        FAULTS.disarm()
        await stop_stack(handles)


@pytest.mark.e2e
async def test_drain_worker_hands_off_to_replica():
    """drain_worker: the drained worker's record goes away (draining ->
    lease revoked), new requests land on the replica, the service refuses
    late arrivals."""
    from dynamo_tpu.launch import drain_worker
    from tests.conftest import start_stack, stop_stack

    handles, base = await start_stack(num_workers=2)
    try:
        victim = handles["services"][0]
        instance_key = victim.instance.key
        done = await drain_worker(handles["runtime"], victim, timeout=10.0)
        assert done is True
        assert victim._draining and victim._closed
        store = handles["runtime"].store
        assert await store.get(instance_key) is None  # lease revoked: record gone
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "prompt": "after drain", "max_tokens": 2, "temperature": 0}
            for _ in range(3):
                async with s.post(base + "/v1/completions", json=body) as r:
                    assert r.status == 200, await r.text()
    finally:
        await stop_stack(handles)
