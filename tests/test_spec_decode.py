"""Lossless speculative decoding on the mixed-step scheduler (ISSUE 6).

The contract under test: with ``spec_k > 0`` the engine emits *bit-identical*
token streams (and logprobs) to ``spec_k = 0`` — greedy and seeded, with
chunked prefill mixing into the same steps — because verification replays
the exact per-token sampling (same rng fold counter, same logits math) and
only commits the matching prefix. Also covered: the n-gram proposer, the
rng-fold-advances-once-per-emitted-token invariant, page rollback
accounting, and the non-contiguous verify routing in the attention layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.engine.spec import NgramProposer, build_proposer
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.ops.attention import paged_attention_reference
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

PAGE = 4
_PARAMS = {}


def params_for(preset):
    if preset not in _PARAMS:
        _PARAMS[preset] = llama.init_params(PRESETS[preset], 0)
    return _PARAMS[preset]


def make_core(preset="test-tiny", *, spec_k=0, chunk=16, num_pages=96,
              max_batch=8, max_seq_len=256, params=None, cache_dtype=None,
              attn_impl="reference", **cfg_kw):
    cfg = PRESETS[preset]
    params = params if params is not None else params_for(preset)
    runner = ModelRunner(
        cfg, params, num_pages=num_pages, page_size=PAGE,
        max_batch_size=max_batch, prefill_bucket=16, attn_impl=attn_impl,
        cache_dtype=cache_dtype,
    )
    return EngineCore(runner, EngineConfig(
        num_pages=num_pages, page_size=PAGE, max_batch_size=max_batch,
        max_seq_len=max_seq_len, chunk_prefill_tokens=chunk, spec_k=spec_k,
        **cfg_kw,
    ))


def run_all(core, reqs, max_steps=300):
    """Drive to completion; returns ({seq_id: tokens}, {seq_id: logprobs})."""
    tokens, lps = {}, {}
    for req in reqs:
        seq = core.add_request(req)
        tokens[seq.seq_id] = []
        lps[seq.seq_id] = []
    steps = 0
    while core.has_work and steps < max_steps:
        for seq, out in core.step():
            tokens[seq.seq_id].extend(out.token_ids)
            if out.logprobs:
                lps[seq.seq_id].extend(out.logprobs)
        steps += 1
    assert not core.has_work, "engine did not drain"
    return tokens, lps


# -- proposer ---------------------------------------------------------------


def test_ngram_proposer_basic_lookup():
    # ...5 6 7 | 5 6 7 -> the trailing 3-gram recurs; propose what followed.
    p = NgramProposer()
    assert p.propose([5, 6, 7, 9, 11, 5, 6, 7], 3) == [9, 11, 5]


def test_ngram_proposer_prefers_longest_then_most_recent():
    p = NgramProposer()
    # Suffix [1, 2] occurs twice earlier; the most recent match (followed by
    # 8) must win over the older one (followed by 4).
    assert p.propose([1, 2, 4, 1, 2, 8, 9, 1, 2], 1) == [8]
    # A longer matching suffix beats a shorter, more recent one.
    assert p.propose([3, 1, 2, 5, 9, 9, 1, 2, 5], 1) == [9]


def test_ngram_proposer_caps_and_empties():
    p = NgramProposer()
    # Period-1 stream: every match is near the end, so the longest
    # truncated continuation wins (start=0 match -> 3 tokens follow it).
    assert p.propose([7, 7, 7, 7, 7, 7], 4) == [7, 7, 7]
    assert p.propose([7, 7, 7, 7], 0) == []
    assert p.propose([1], 4) == []  # too short to have an earlier match
    assert p.propose([1, 2, 3, 4], 4) == []  # no repetition at all
    # max_k caps the continuation even when more history is available.
    assert len(p.propose(list(range(8)) * 4, 3)) == 3


def test_build_proposer_factory():
    assert isinstance(build_proposer(), NgramProposer)
    with pytest.raises(ValueError):
        build_proposer("draft-model-7b")


# -- losslessness -----------------------------------------------------------


def _requests(vocab):
    """A mix that exercises verify + chunked prefill + seeded sampling."""
    return [
        # Periodic prompt: the drafter matches and verification accepts.
        PreprocessedRequest(
            token_ids=[5, 7, 5, 7, 5, 7, 9, 11],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=20, ignore_eos=True),
        ),
        # Long prompt: chunked prefill rides the same spec dispatches.
        PreprocessedRequest(
            token_ids=[i % (vocab - 2) + 1 for i in range(40)],
            sampling=SamplingOptions(temperature=0.8, seed=42, logprobs=3),
            stop=StopConditions(max_tokens=12, ignore_eos=True),
        ),
        PreprocessedRequest(
            token_ids=[3, 3, 3, 3, 2, 1],
            sampling=SamplingOptions(temperature=0.7, seed=7),
            stop=StopConditions(max_tokens=12, ignore_eos=True),
        ),
    ]


@pytest.mark.parametrize("preset", ["test-tiny", "test-tiny-mla"])
@pytest.mark.parametrize("spec_k", [1, 3, 4])
def test_spec_decode_is_lossless(preset, spec_k):
    vocab = PRESETS[preset].vocab_size
    base_tok, base_lp = run_all(make_core(preset), _requests(vocab))
    core = make_core(preset, spec_k=spec_k)
    spec_tok, spec_lp = run_all(core, _requests(vocab))
    assert spec_tok == base_tok
    assert spec_lp == base_lp
    assert core.spec_tokens_proposed > 0  # the path actually engaged


def test_spec_decode_lossless_without_chunking():
    """chunk_prefill_tokens=0 (phase-exclusive prefill) still speculates on
    pure-decode steps — the spec path must not depend on mixed chunks."""
    vocab = PRESETS["test-tiny"].vocab_size
    base_tok, base_lp = run_all(make_core(chunk=0), _requests(vocab))
    core = make_core(chunk=0, spec_k=4)
    spec_tok, spec_lp = run_all(core, _requests(vocab))
    assert spec_tok == base_tok
    assert spec_lp == base_lp
    assert core.spec_tokens_proposed > 0


def test_spec_lossless_on_fp8_kv_cache(monkeypatch):
    """KV dtype is orthogonal to losslessness: with the SAME fp8 cache,
    spec_k>0 must still reproduce spec_k=0 bit-for-bit (every attention
    path upcasts fp8 storage identically). Also pins the launch-side
    DYN_KV_CACHE_DTYPE resolution that feeds ModelRunner(cache_dtype=...)."""
    from dynamo_tpu.launch import _kv_cache_dtype

    monkeypatch.setenv("DYN_KV_CACHE_DTYPE", "fp8")
    assert _kv_cache_dtype() == jnp.float8_e4m3fn
    monkeypatch.setenv("DYN_KV_CACHE_DTYPE", "bf16")
    assert _kv_cache_dtype() is None  # runner keeps its model-dtype default
    monkeypatch.setenv("DYN_KV_CACHE_DTYPE", "int4")
    with pytest.raises(ValueError):
        _kv_cache_dtype()

    vocab = PRESETS["test-tiny"].vocab_size
    base_core = make_core(cache_dtype=jnp.float8_e4m3fn)
    assert base_core.runner.k_cache.dtype == jnp.float8_e4m3fn
    base_tok, base_lp = run_all(base_core, _requests(vocab))
    spec_tok, spec_lp = run_all(
        make_core(spec_k=4, cache_dtype=jnp.float8_e4m3fn), _requests(vocab)
    )
    assert spec_tok == base_tok
    assert spec_lp == base_lp


# -- acceptance + rng fold discipline ---------------------------------------


def _flat_params():
    """Zeroed weights: every logit is identical, greedy argmax is always
    token 0, so generation is maximally repetitive — the drafter proposes
    [0, 0, ...] and verification must accept every draft."""
    return jax.tree.map(jnp.zeros_like, params_for("test-tiny"))


def test_acceptance_positive_on_repetitive_stream():
    core = make_core(spec_k=4, params=_flat_params())
    req = PreprocessedRequest(
        token_ids=[1, 2, 3, 4],
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=24, ignore_eos=True),
    )
    toks, _ = run_all(core, [req])
    assert toks[0] == [0] * 24
    assert core.spec_tokens_proposed > 0
    assert core.spec_tokens_accepted > 0
    # All-zero stream + always-argmax-0 target: every draft token accepted.
    assert core.spec_tokens_accepted == core.spec_tokens_proposed
    # The counters feed the flight recorder / metrics acceptance rate.
    assert core.spec_steps > 0


def test_rng_fold_advances_once_per_emitted_token():
    """sample_steps handed to the verify dispatch must equal the number of
    tokens emitted so far — fold advances exactly once per emitted token,
    never per dispatch and never for rejected drafts."""
    core = make_core(spec_k=4, params=_flat_params())
    calls = []
    orig = core.runner.spec_step

    def spy(batch, verify_width, lp_k=0):
        calls.append(int(np.asarray(batch.sample_steps)[0]))
        return orig(batch, verify_width, lp_k=lp_k)

    core.runner.spec_step = spy
    seq = core.add_request(PreprocessedRequest(
        token_ids=[1, 2, 3, 4],
        sampling=SamplingOptions(temperature=0.9, seed=11),
        stop=StopConditions(max_tokens=16, ignore_eos=True),
    ))
    emitted = 0
    steps = 0
    while core.has_work and steps < 100:
        before = len(calls)
        outs = core.step()
        if len(calls) > before:
            assert calls[-1] == emitted
        emitted += sum(len(o.token_ids) for _, o in outs)
        steps += 1
    assert emitted == 16
    assert len(calls) > 0
    # Every emitted token advanced the fold exactly once: the final fold
    # counter the engine would use next equals the total emitted.
    assert seq.num_generated == emitted


def test_pages_released_after_spec_requests_finish():
    """Rejected-draft page rollback + normal teardown: nothing leaks."""
    core = make_core(spec_k=4)
    vocab = PRESETS["test-tiny"].vocab_size
    run_all(core, _requests(vocab))
    stats = core.allocator.stats()
    assert stats.active_pages == 0


def test_draft_len_respects_max_seq_len():
    """A request one token from its limit must not speculate past it."""
    core = make_core(spec_k=4, params=_flat_params(), max_seq_len=16)
    req = PreprocessedRequest(
        token_ids=[1, 2, 3, 4],
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=64, ignore_eos=True),
    )
    toks, _ = run_all(core, [req])
    assert len(toks[0]) == 12  # capped by max_seq_len, not max_tokens
    assert core.allocator.stats().active_pages == 0


# -- verify-path attention routing ------------------------------------------


def test_pallas_rejects_gappy_rows_without_flag(monkeypatch):
    from dynamo_tpu.ops.pallas_paged import paged_attention_pallas

    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 3, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((9, 4, 128)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((9, 4, 128)), jnp.float32)
    tables = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    gappy = jnp.asarray([[4, 6, 7]], jnp.int32)  # non-contiguous verify row
    with pytest.raises(ValueError, match="contiguous"):
        paged_attention_pallas(q, k, v, tables, gappy, scale=0.125)
    # Declaring non-contiguous routes to the multi-query decode kernel
    # (per-row causal mask — exact for gappy verify layouts) instead of
    # raising; its online softmax agrees with the reference to float
    # accumulation-order tolerance.
    out = paged_attention_pallas(
        q, k, v, tables, gappy, scale=0.125, contiguous_positions=False
    )
    want = paged_attention_reference(q, k, v, tables, gappy, scale=0.125)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_multi_token_verify_row_matches_per_position_decode_kernel():
    """The reference formulation the verify dispatch routes through agrees
    with the Pallas decode kernel (interpret mode) scored one position at a
    time — i.e. a K+1-wide verify row attends exactly as K+1 sequential
    decodes would."""
    from dynamo_tpu.ops.pallas_paged import decode_supported, paged_decode_attention

    rng = np.random.default_rng(1)
    b, t, n_heads, n_kv, hd, ps, pps = 2, 3, 4, 2, 64, 4, 8
    width = n_kv * hd
    num_pages = b * pps + 1
    k = jnp.asarray(rng.standard_normal((num_pages, ps, width)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((num_pages, ps, width)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, t, n_heads, hd)), jnp.float32)
    tables = jnp.asarray(
        1 + rng.permutation(num_pages - 1)[: b * pps].reshape(b, pps), jnp.int32
    )
    starts = np.asarray([9, 17])  # verify rows resume mid-sequence
    positions = jnp.asarray(starts[:, None] + np.arange(t)[None, :], jnp.int32)
    scale = hd**-0.5
    assert decode_supported(q[:, :1], k)

    whole = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    per_pos = [
        paged_decode_attention(
            q[:, j:j + 1], k, v, tables, positions[:, j:j + 1],
            scale=scale, interpret=True,
        )
        for j in range(t)
    ]
    got = jnp.concatenate(per_pos, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(whole), rtol=2e-5, atol=2e-5)


# -- kernel-path verify (ISSUE 7) -------------------------------------------


def _pin_kernel_block_shape(monkeypatch):
    """Pin the kernel's block partition to static values: _pages_per_block
    normally depends on the padded pages bucket, which can differ between a
    spec run (speculative pages allocated) and its spec_k=0 baseline at the
    same logical step — a different accumulation partition is a different
    float result. Bit-parity asserts need both runs on identical partitions."""
    import dynamo_tpu.ops.pallas_mla as pm
    import dynamo_tpu.ops.pallas_paged as pp

    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("DYN_DECODE_SPLITS", "1")

    def pin(pps, ps, *a):
        return min(pps, 4)

    monkeypatch.setattr(pp, "_pages_per_block", pin)
    # pallas_mla binds the helper by name at import time.
    monkeypatch.setattr(pm, "_pages_per_block", pin)


@pytest.mark.parametrize("preset", ["test-tiny", "test-tiny-mla"])
def test_spec_decode_lossless_on_kernel_path(monkeypatch, preset):
    """spec_step dispatch reaches the Pallas kernel (multi-query verify
    rows) and stays bit-identical to the spec_k=0 baseline — tokens AND
    logprobs. chunk=0 so prompts dispatch identically in both runs (whole
    prefills via runner.step) and every decode/verify step is a kernel
    dispatch."""
    import dynamo_tpu.ops.pallas_paged as pp

    _pin_kernel_block_shape(monkeypatch)
    vocab = PRESETS[preset].vocab_size
    before = pp.fallback_snapshot()
    base_tok, base_lp = run_all(
        make_core(preset, spec_k=0, chunk=0, attn_impl="pallas"), _requests(vocab)
    )
    spec_core = make_core(preset, spec_k=3, chunk=0, attn_impl="pallas")
    spec_tok, spec_lp = run_all(spec_core, _requests(vocab))
    after = pp.fallback_snapshot()
    assert spec_core.spec_tokens_accepted > 0  # speculation actually engaged
    assert spec_tok == base_tok
    assert spec_lp == base_lp
    # Decode and verify must have run on the kernel, not the gather path.
    grew = [s for s in after if after[s] > before.get(s, 0)]
    bad = [s for s in grew
           if s.startswith(("decode:", "verify:", "mla_decode:", "mla_verify:"))]
    assert not bad, bad


def test_spec_chunked_verify_rides_kernel(monkeypatch):
    """chunk > 0: mixed steps widen verify batches to the chunk width; that
    still fits the kernel's T cap, so no verify fallback is recorded."""
    import dynamo_tpu.ops.pallas_paged as pp

    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")
    vocab = PRESETS["test-tiny"].vocab_size
    core = make_core(spec_k=3, chunk=16, attn_impl="pallas")
    before = pp.fallback_snapshot()
    toks, _ = run_all(core, _requests(vocab))
    after = pp.fallback_snapshot()
    assert core.spec_tokens_accepted > 0
    grew = [s for s in after if after[s] > before.get(s, 0)]
    assert not [s for s in grew if s.startswith(("verify:", "decode:"))], grew
    assert all(len(t) > 0 for t in toks.values())
