"""Tests for the discovery store: put/get/watch, leases, cascade expiry."""

import asyncio

import pytest

from dynamo_tpu.runtime.discovery import MemoryStore, WatchEventType


async def test_put_get_delete():
    store = MemoryStore()
    await store.put("a/b", b"1")
    assert await store.get("a/b") == b"1"
    await store.put("a/b", b"2")
    assert await store.get("a/b") == b"2"
    assert await store.delete("a/b") is True
    assert await store.delete("a/b") is False
    assert await store.get("a/b") is None


async def test_get_prefix():
    store = MemoryStore()
    await store.put("models/ns/x", b"x")
    await store.put("models/ns/y", b"y")
    await store.put("instances/ns/z", b"z")
    got = await store.get_prefix("models/ns/")
    assert got == {"models/ns/x": b"x", "models/ns/y": b"y"}


async def test_put_if_absent():
    store = MemoryStore()
    assert await store.put_if_absent("k", b"first") is True
    assert await store.put_if_absent("k", b"second") is False
    assert await store.get("k") == b"first"


async def test_watch_snapshot_and_live_events():
    store = MemoryStore()
    await store.put("pre/a", b"1")
    events = []

    async def watcher():
        async for ev in store.watch_prefix("pre/"):
            events.append(ev)
            if len(events) == 3:
                return

    task = asyncio.create_task(watcher())
    await asyncio.sleep(0.05)
    await store.put("pre/b", b"2")
    await store.put("other/c", b"x")  # outside prefix: not delivered
    await store.delete("pre/a")
    await asyncio.wait_for(task, timeout=5)
    assert [(e.type, e.key) for e in events] == [
        (WatchEventType.PUT, "pre/a"),
        (WatchEventType.PUT, "pre/b"),
        (WatchEventType.DELETE, "pre/a"),
    ]


async def test_lease_expiry_cascades_and_notifies():
    store = MemoryStore(reap_interval=0.05)
    lease = await store.create_lease(ttl=0.15)
    await store.put("instances/w1", b"i", lease_id=lease.id)
    await store.put("unleased", b"u")
    deletes = []

    async def watcher():
        async for ev in store.watch_prefix("instances/", initial=False):
            if ev.type is WatchEventType.DELETE:
                deletes.append(ev.key)
                return

    task = asyncio.create_task(watcher())
    await asyncio.sleep(0.4)  # no keep-alive -> lease expires
    await asyncio.wait_for(task, timeout=5)
    assert deletes == ["instances/w1"]
    assert await store.get("instances/w1") is None
    assert await store.get("unleased") == b"u"
    await store.close()


async def test_keep_alive_extends_lease():
    store = MemoryStore(reap_interval=0.05)
    lease = await store.create_lease(ttl=0.2)
    await store.put("k", b"v", lease_id=lease.id)
    for _ in range(5):
        await asyncio.sleep(0.1)
        await lease.keep_alive()
    assert await store.get("k") == b"v"
    await lease.revoke()
    assert await store.get("k") is None
    with pytest.raises(KeyError):
        await store.keep_alive(lease.id)
    await store.close()


async def test_put_with_unknown_lease_rejected():
    store = MemoryStore()
    with pytest.raises(KeyError):
        await store.put("k", b"v", lease_id=999)
