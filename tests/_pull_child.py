"""Receiver process for tests/test_pull_two_process.py.

Runs a decode-side engine core with a KvTransferService on a real
TcpTransport. Mode "wire" installs the socket-backed pull wire
(tests/_pull_wire.py) so phase-2 pulls fetch bytes from the sender
process; mode "unsupported" forces the capability probe to False so the
phase-1 query answers pull_unsupported and the sender must fall back to
the packed-bytes stream.

Prints ``ADDR <kv_transfer addr> <kv_read addr>`` once serving, then waits
for stdin EOF.
"""

import asyncio
import os
import sys

MODE = sys.argv[1]  # "wire" | "unsupported"

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))  # repo root


async def main() -> None:
    from dynamo_tpu.disagg.pull_transport import set_transport
    from dynamo_tpu.disagg.transfer import KV_TRANSFER_ENDPOINT, KvTransferService
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.runtime.engine import AsyncEngine, Context
    from dynamo_tpu.runtime.tcp import TcpTransport

    if MODE == "wire":
        from _pull_wire import SocketWireTransport

        set_transport(SocketWireTransport(), supported=True)
    else:
        set_transport(None, supported=False)

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    runner = ModelRunner(
        cfg, params, num_pages=32, page_size=4, max_batch_size=4,
        prefill_bucket=16, attn_impl="reference",
    )
    config = EngineConfig(
        num_pages=32, page_size=4, max_batch_size=4,
        max_prefill_tokens=128, max_seq_len=128,
    )
    core = EngineCore(runner, config)
    svc = KvTransferService(core)

    class KvRead(AsyncEngine):
        """Test-only readback: the parent verifies injected page CONTENT."""

        async def generate(self, request, context: Context):
            pages = core.allocator.match_prefix(request["hashes"])
            try:
                payloads = core.runner.read_pages(pages)
                yield {
                    "n": len(pages),
                    "k": [k.tobytes() for k, _v in payloads],
                    "v": [v.tobytes() for _k, v in payloads],
                }
            finally:
                core.allocator.release(pages)

    transport = TcpTransport(host="127.0.0.1")
    await transport.register_engine(KV_TRANSFER_ENDPOINT, svc)
    await transport.register_engine("kv_read", KvRead())
    print(
        "ADDR",
        transport.address_of(KV_TRANSFER_ENDPOINT),
        transport.address_of("kv_read"),
        flush=True,
    )
    await asyncio.get_running_loop().run_in_executor(None, sys.stdin.read)
    await transport.close()


asyncio.run(main())
