"""Wire-v2 chunked KV stream: golden parity with the monolithic path,
mid-stream failure rollback, and decode interleave during a transfer.

The sender (`send_blocks_chunked`) double-buffers: chunk N+1's device gather
+ D2H DMA is dispatched before chunk N is packed and sent; the receiver
(`KvTransferService._ingest_chunk`) scatters and commits each chunk
incrementally under session pins. docs/KV_TRANSFER_WIRE_V2.md specifies the
framing these tests enforce.
"""

import asyncio
from types import SimpleNamespace

import numpy as np
import pytest

from dynamo_tpu.disagg.transfer import (
    KvTransferService,
    collect_prefill_blocks,
    pack_block,
    send_blocks,
    send_blocks_chunked,
)
from dynamo_tpu.engine.allocator import PageAllocator
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transport import InMemoryTransport
from dynamo_tpu.tokens import compute_block_hashes

CFG = PRESETS["test-tiny"]
PAGE = 4


def _core(num_pages: int = 16) -> SimpleNamespace:
    params = llama.init_params(CFG, 0)
    runner = ModelRunner(CFG, params, num_pages=num_pages, page_size=PAGE, max_batch_size=2)
    return SimpleNamespace(allocator=PageAllocator(num_pages, PAGE), runner=runner)


def _commit_chain(core, hashes, seed=0):
    """Commit a hash chain of random KV pages; returns {hash: (k, v)}."""
    rng = np.random.default_rng(seed)
    pids = core.allocator.allocate(len(hashes))
    parent = None
    ks, vs = [], []
    for pid, h in zip(pids, hashes):
        core.allocator.commit(pid, h, parent)
        parent = h
        ks.append(rng.standard_normal((CFG.num_layers, PAGE, CFG.kv_dim)).astype(np.float32))
        vs.append(rng.standard_normal((CFG.num_layers, PAGE, CFG.kv_dim)).astype(np.float32))
    core.runner.write_pages(pids, ks, vs)
    core.allocator.release(pids)
    return {h: (k, v) for h, k, v in zip(hashes, ks, vs)}


def _zero_blocks(hashes):
    zeros = np.zeros((CFG.num_layers, PAGE, CFG.kv_dim), np.float32)
    parent = None
    out = []
    for h in hashes:
        out.append(pack_block(h, parent, [], zeros, zeros))
        parent = h
    return out


async def test_chunked_stream_golden_vs_monolithic():
    """5 pages at chunk_pages=2 (uneven: 2+2+1 chunks) land byte-identical
    to the source AND to the v1 collect-then-send path, with the chain
    linkage intact and no session state or pins left behind."""
    src = _core()
    hashes = compute_block_hashes(list(range(5 * PAGE)), PAGE, salt=0)
    payloads = _commit_chain(src, hashes)

    transport = InMemoryTransport()
    dst_v2, dst_v1 = _core(), _core()
    svc_v2, svc_v1 = KvTransferService(dst_v2), KvTransferService(dst_v1)
    await transport.register_engine("kv_v2", svc_v2)
    await transport.register_engine("kv_v1", svc_v1)

    out = await send_blocks_chunked(
        transport, "mem://kv_v2", "r1", src, hashes, chunk_pages=2)
    assert out["injected"] == 5 and out["total"] == 5 and out["last"]
    assert out["seq"] == 2  # 3 chunks: the pipeline really ran chunked
    assert out["bytes"] == sum(k.nbytes + v.nbytes for k, v in payloads.values())
    assert set(out["phases"]) == {"gather_s", "pack_s", "wire_s"}

    blocks = collect_prefill_blocks(src, hashes)
    out_v1 = await send_blocks(transport, "mem://kv_v1", "r1", blocks)
    assert out_v1["injected"] == 5

    for core in (dst_v2, dst_v1):
        pids = core.allocator.match_prefix(hashes)
        assert len(pids) == 5  # full chain matchable: linkage committed
        for pid, h in zip(pids, hashes):
            k_got, v_got = core.runner.read_page(pid)
            np.testing.assert_array_equal(k_got, payloads[h][0])
            np.testing.assert_array_equal(v_got, payloads[h][1])
        core.allocator.release(pids)
    # Stream closed cleanly: no session, no leaked pins on either side.
    assert svc_v2.stats()["streams_in_flight"] == 0
    again = src.allocator.match_prefix(hashes)
    assert len(again) == 5
    src.allocator.release(again)


async def test_midstream_sender_death_rolls_back():
    """A stream whose sender dies after chunk 0 is reclaimed by the sweep:
    the session's pins drop, the committed prefix stays matchable but
    becomes ordinary evictable cache (clear_cache reclaims every page)."""
    dst = _core()
    svc = KvTransferService(dst)
    hashes = compute_block_hashes(list(range(4 * PAGE)), PAGE, salt=0)
    free0 = dst.allocator.num_free()

    async def send(req):
        async for out in svc.generate(req, Context()):
            return out

    out = await send({"request_id": "dead", "seq": 0,
                      "blocks": _zero_blocks(hashes)[:2], "last": False})
    assert out["injected"] == 2
    assert svc.stats()["streams_in_flight"] == 1
    # Session pins hold the chunk: nothing allocatable from those 2 pages.
    assert dst.allocator.num_free() == free0 - 2

    # Sender dies; the abandoned-stream sweep fires (age threshold 0).
    svc.PENDING_PULL_MAX_AGE = 0.0
    await asyncio.sleep(0.01)
    svc._sweep_pending_pulls()
    assert svc.stats()["streams_in_flight"] == 0
    # The committed prefix is still a valid, matchable chain...
    pids = dst.allocator.match_prefix(hashes[:2])
    assert len(pids) == 2
    dst.allocator.release(pids)
    # ...but unpinned: eviction reclaims it all the way back.
    dst.allocator.clear_cache()
    assert dst.allocator.num_free() == free0


async def test_out_of_order_seq_is_a_stream_error():
    """A seq gap means lost chunks: the receiver rolls the stream back and
    reports stream_error (the sender raises and falls back to v1). A fresh
    seq-0 for the same request id replaces any stale session."""
    dst = _core()
    svc = KvTransferService(dst)
    hashes = compute_block_hashes(list(range(3 * PAGE)), PAGE, salt=0)
    blocks = _zero_blocks(hashes)

    async def send(req):
        async for out in svc.generate(req, Context()):
            return out

    out = await send({"request_id": "r", "seq": 0, "blocks": blocks[:1], "last": False})
    assert out["injected"] == 1
    out = await send({"request_id": "r", "seq": 2, "blocks": blocks[1:2], "last": False})
    assert "unexpected seq 2" in out["stream_error"]
    assert svc.stats()["streams_in_flight"] == 0  # rolled back
    # A chunk for a dead stream is also an error (no session).
    out = await send({"request_id": "r", "seq": 1, "blocks": blocks[1:2], "last": False})
    assert "no session" in out["stream_error"]
    # Reconnect restarts at seq 0 and completes; chunk-0 blocks are hits.
    out = await send({"request_id": "r", "seq": 0, "blocks": blocks, "last": True})
    assert out["injected"] == 3 and out["total"] == 3
    assert svc.stats()["streams_in_flight"] == 0
    pids = dst.allocator.match_prefix(hashes)
    assert len(pids) == 3
    dst.allocator.release(pids)


async def test_sender_abort_notifies_receiver():
    """send_blocks_chunked dying mid-stream best-effort aborts the receiver
    session before the caller falls back to the monolithic path."""
    src = _core()
    hashes = compute_block_hashes(list(range(4 * PAGE)), PAGE, salt=0)
    _commit_chain(src, hashes)
    dst = _core()
    svc = KvTransferService(dst)
    transport = InMemoryTransport()
    await transport.register_engine("kv", svc)

    real_pack = pack_block
    calls = {"n": 0}

    def dying_pack(*a, **kw):
        calls["n"] += 1
        if calls["n"] > 2:  # chunk 0 (2 pages) packs fine; chunk 1 dies
            raise RuntimeError("sender died mid-pack")
        return real_pack(*a, **kw)

    import dynamo_tpu.disagg.transfer as transfer_mod
    orig = transfer_mod.pack_block
    transfer_mod.pack_block = dying_pack
    try:
        with pytest.raises(RuntimeError, match="sender died"):
            # streams=0 pins the legacy v2 protocol: this test exercises the
            # v2 sender's pack path specifically (v3 packs via
            # pack_chunk_blob; its abort drill lives in test_kv_wire.py).
            await send_blocks_chunked(
                transport, "mem://kv", "r", src, hashes, chunk_pages=2, streams=0)
    finally:
        transfer_mod.pack_block = orig
    # The abort frame cleaned the receiver up; no pins, no session.
    assert svc.stats()["streams_in_flight"] == 0
    free0 = dst.allocator.num_free()
    dst.allocator.clear_cache()
    assert dst.allocator.num_free() >= free0
    # Sender released its chain refcounts despite the failure.
    again = src.allocator.match_prefix(hashes)
    assert len(again) == 4
    src.allocator.release(again)


async def test_decode_steps_interleave_with_inflight_stream():
    """The sender's io_lock is held per-chunk-dispatch only: a concurrent
    decode step must get the lock repeatedly WHILE a chunked transfer with a
    slow receiver is in flight (the v1 path gathered everything under one
    hold)."""
    import threading
    import time as _time

    src = _core(num_pages=32)
    hashes = compute_block_hashes(list(range(6 * PAGE)), PAGE, salt=0)
    _commit_chain(src, hashes)
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    real_write_pages = dst.runner.write_pages

    def slow_write_pages(*a, **kw):
        _time.sleep(0.05)  # make each chunk's ingest span measurable
        return real_write_pages(*a, **kw)

    dst.runner.write_pages = slow_write_pages
    transport = InMemoryTransport()
    await transport.register_engine("kv", svc)

    done = threading.Event()
    acquisitions = 0

    def hammer():
        nonlocal acquisitions
        while not done.is_set():
            if src.runner.io_lock.acquire(timeout=0.01):
                try:
                    if not done.is_set():
                        acquisitions += 1
                finally:
                    src.runner.io_lock.release()
            _time.sleep(0.005)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        out = await send_blocks_chunked(
            transport, "mem://kv", "r", src, hashes, chunk_pages=1)
    finally:
        done.set()
        t.join()
    assert out["injected"] == 6
    assert acquisitions >= 2, (
        f"io_lock only obtainable {acquisitions}x during a 6-chunk stream"
    )
