"""Golden-logit parity for the VLM path vs HF transformers LLaVA.

Same technique as tests/test_golden.py (VERDICT r3 item 4): a tiny seeded
HF LlavaForConditionalGeneration is saved as a real checkpoint, loaded
through ``load_vlm`` (CLIP tower + projector + renamed-LM mapping), and an
image request — pixel tensor through ``encode_image``, embeddings spliced
over the placeholder tokens via ``llama.forward(mm_embeds=...)`` — must
reproduce HF's logits. This pins: the conv->matmul patch embedding
conversion, CLS/pre-LN/bias/quick_gelu CLIP semantics, the
vision_feature_layer=-2 selection, projector mapping, the language_model
weight-name translation, and placeholder substitution.

Reference parity target: `examples/multimodal/components/encode_worker.py:61-179`
(serves the HF tower; here the tower is first-party JAX).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.loader import load_vlm  # noqa: E402
from dynamo_tpu.models.vision import encode_image  # noqa: E402

IMAGE_TOKEN = 250


def _tiny_llava():
    from transformers import CLIPVisionConfig, LlamaConfig, LlavaConfig, LlavaForConditionalGeneration

    torch.manual_seed(0)
    cfg = LlavaConfig(
        vision_config=CLIPVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=2, image_size=32, patch_size=8,
        ),
        text_config=LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, tie_word_embeddings=False, rope_theta=10000.0,
        ),
        image_token_index=IMAGE_TOKEN,
    )
    return LlavaForConditionalGeneration(cfg).eval().float()


def test_golden_llava_image_logits(tmp_path):
    m = _tiny_llava()
    m.save_pretrained(str(tmp_path), safe_serialization=True)

    tcfg, vcfg, lm_params, vis_params = load_vlm(tmp_path, dtype="float32")
    assert tcfg.image_token_id == IMAGE_TOKEN
    assert vcfg.cls_token and vcfg.pre_ln and vcfg.act == "quick_gelu"
    n_img = vcfg.num_patches  # 16 placeholder tokens at 32px / patch 8

    rng = np.random.default_rng(0)
    pixels_hwc = rng.standard_normal((1, 32, 32, 3)).astype(np.float32) * 0.5
    prompt = [3, 7] + [IMAGE_TOKEN] * n_img + [11, 42, 99, 5]
    t = len(prompt)

    # HF reference.
    with torch.no_grad():
        hf_logits = m(
            input_ids=torch.tensor([prompt]),
            pixel_values=torch.tensor(pixels_hwc.transpose(0, 3, 1, 2)),
        ).logits[0].float().numpy()

    # Ours: encode -> substitute -> paged forward.
    mm = encode_image(vis_params, vcfg, jnp.asarray(pixels_hwc))
    assert mm.shape == (1, n_img, tcfg.hidden_size)

    page_size = 8
    k_cache, v_cache = llama.init_kv_cache(tcfg, num_pages=8, page_size=page_size)
    n_pages = -(-t // page_size)
    tables = jnp.asarray([list(range(1, 1 + n_pages))], jnp.int32)
    positions = jnp.arange(t, dtype=jnp.int32)[None]
    slots = jnp.take_along_axis(tables, positions // page_size, axis=1) * page_size + positions % page_size
    ours, k_cache, v_cache = llama.forward(
        lm_params, tcfg, jnp.asarray([prompt], jnp.int32), positions,
        k_cache, v_cache, tables, slots, jnp.asarray([t - 1], jnp.int32),
        mm_embeds=mm,
    )
    # forward returns the LAST position's logits only ([B, V]).
    np.testing.assert_allclose(
        np.asarray(ours)[0], hf_logits[t - 1], atol=2e-3, rtol=1e-3,
    )

    # One decode step on the image-conditioned cache must also match.
    tok = 42
    pos = jnp.asarray([[t]], jnp.int32)
    slot = jnp.take_along_axis(tables, pos // page_size, axis=1) * page_size + pos % page_size
    ours2, _, _ = llama.forward(
        lm_params, tcfg, jnp.asarray([[tok]], jnp.int32), pos,
        k_cache, v_cache, tables, slot, jnp.asarray([0], jnp.int32),
    )
    with torch.no_grad():
        hf2 = m(
            input_ids=torch.tensor([prompt + [tok]]),
            pixel_values=torch.tensor(pixels_hwc.transpose(0, 3, 1, 2)),
        ).logits[0, -1].float().numpy()
    np.testing.assert_allclose(np.asarray(ours2)[0], hf2, atol=2e-3, rtol=1e-3)


def test_golden_llava_tower_alone(tmp_path):
    """The tower+projector in isolation against HF's get_image_features —
    localizes failures to vision vs LM."""
    m = _tiny_llava()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    _tcfg, vcfg, _lm, vis_params = load_vlm(tmp_path, dtype="float32")

    rng = np.random.default_rng(1)
    pixels_hwc = rng.standard_normal((2, 32, 32, 3)).astype(np.float32) * 0.5
    with torch.no_grad():
        want = m.get_image_features(
            pixel_values=torch.tensor(pixels_hwc.transpose(0, 3, 1, 2)),
        )
        if isinstance(want, (list, tuple)):
            want = torch.cat([w[None] if w.ndim == 2 else w for w in want])
        want = want.float().numpy()
    got = np.asarray(encode_image(vis_params, vcfg, jnp.asarray(pixels_hwc)))
    np.testing.assert_allclose(got.reshape(want.shape), want, atol=2e-4, rtol=1e-3)


@pytest.mark.e2e
async def test_real_vlm_checkpoint_served_e2e(tmp_path):
    """A real (tiny, seeded) LLaVA checkpoint DIRECTORY served through the
    full HTTP stack: loader -> real CLIP tower in the encode worker ->
    placeholder splice -> prefill. Pixels must matter."""
    import base64
    import io

    import aiohttp
    from PIL import Image

    from dynamo_tpu.launch import run_local

    m = _tiny_llava()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    name = tmp_path.name

    def data_url(color):
        img = Image.new("RGB", (32, 32), color)
        buf = io.BytesIO()
        img.save(buf, format="PNG")
        return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()

    handles = await run_local(str(tmp_path), port=0, num_pages=128, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        async def ask(color):
            body = {
                "model": name,
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "what is this? "},
                    {"type": "image_url", "image_url": {"url": data_url(color)}},
                ]}],
                "max_tokens": 6, "temperature": 0,
            }
            async with aiohttp.ClientSession() as s:
                async with s.post(base + "/v1/chat/completions", json=body) as r:
                    assert r.status == 200, await r.text()
                    return await r.json()

        red = await ask((255, 0, 0))
        blue = await ask((0, 0, 255))
        assert red["usage"]["prompt_tokens"] > 16  # placeholders accounted
        assert red["choices"][0]["message"]["content"] != blue["choices"][0]["message"]["content"]

        from dynamo_tpu.encode import EncodeService
        enc = next(s for s in handles["services"] if isinstance(s, EncodeService))
        assert enc.images_encoded == 2
        # The REAL tower (CLS + CLIP semantics), not the random-init default.
        assert enc.cfg.cls_token and enc.cfg.act == "quick_gelu"
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
