"""Multi-tier block manager tests: tier LRU/priority eviction, G2->G3
cascade, and the engine-integration E2E — KV evicted from HBM is onboarded
back from host/disk tiers with token-exact results."""

import numpy as np

from dynamo_tpu.blocks import BlockManagerConfig, KvBlockManager, TierPool
from dynamo_tpu.blocks.storage import DiskStorage, HostStorage, NullStorage
from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from tests.test_engine_core import greedy_reference, greedy_request, run_to_completion

CFG = PRESETS["test-tiny"]
PARAMS = llama.init_params(CFG, 0)
PAGE = 4


def payload(i):
    k = np.full((2, 4, 2, 16), i, np.float32)
    return k, k + 1


# -- tier pool ---------------------------------------------------------------


def test_tier_put_get_lru_eviction():
    evicted = []
    pool = TierPool("t", HostStorage(), 2, on_evict=lambda h, p: evicted.append(h))
    pool.put(1, payload(1))
    pool.put(2, payload(2))
    assert pool.get(1) is not None  # touch 1 -> 2 becomes LRU
    pool.put(3, payload(3))
    assert evicted == [2]
    assert 2 not in pool and 1 in pool and 3 in pool


def test_tier_priority_evicts_low_first():
    pool = TierPool("t", HostStorage(), 2)
    pool.put(1, payload(1), priority=5)
    pool.put(2, payload(2), priority=0)
    pool.put(3, payload(3), priority=5)
    assert 2 not in pool  # low priority evicted despite being more recent


def test_null_storage_counts_without_payloads():
    pool = TierPool("t", NullStorage(), 4)
    pool.put(1, payload(1))
    assert 1 in pool
    assert pool.get(1) is None  # payload lost by design; entry dropped
    assert 1 not in pool


def test_disk_storage_roundtrip(tmp_path):
    st = DiskStorage(tmp_path / "g3")
    k, v = payload(7)
    st.write(7, (k, v))
    rk, rv = st.read(7)
    np.testing.assert_array_equal(rk, k)
    np.testing.assert_array_equal(rv, v)
    st.delete(7)
    assert st.read(7) is None


def test_manager_cascade_g2_to_g3(tmp_path):
    cfg = BlockManagerConfig(g2_capacity_blocks=2, g3_capacity_blocks=4, g3_path=tmp_path / "g3")
    pages = {i: payload(i) for i in range(8)}
    mgr = KvBlockManager(cfg, read_page=lambda pid: pages[pid], write_page=lambda *a: None)
    mgr.offload(101, 1)
    mgr.offload(102, 2)
    mgr.offload(103, 3)  # evicts 101 from G2 -> cascades to G3
    assert 101 in mgr.g3 and 101 not in mgr.g2
    got = mgr.lookup(101)  # G3 hit promotes back to G2
    assert got is not None and 101 in mgr.g2
    np.testing.assert_array_equal(got[0], pages[1][0])


def test_manager_cascade_to_g4_remote(tmp_path):
    """G2 -> G3 -> G4 cascade over a real (in-memory) object store, and a G4
    hit promoting back to G2 — the cross-worker reuse path."""
    import asyncio
    import threading

    from dynamo_tpu.blocks.storage import RemoteStorage
    from dynamo_tpu.runtime.discovery import MemoryStore
    from dynamo_tpu.runtime.objects import ObjectStore

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        remote = RemoteStorage(ObjectStore(MemoryStore()), loop)
        cfg = BlockManagerConfig(
            g2_capacity_blocks=1, g3_capacity_blocks=1, g3_path=tmp_path / "g3",
            g4_capacity_blocks=4,
        )
        pages = {i: payload(i) for i in range(8)}
        mgr = KvBlockManager(
            cfg, read_page=lambda pid: pages[pid], write_page=lambda *a: None,
            g4_storage=remote,
        )
        mgr.offload(201, 1)
        mgr.offload(202, 2)  # 201 -> G3
        mgr.offload(203, 3)  # 202 -> G3, 201 -> G4
        assert 201 in mgr.g4 and 201 not in mgr.g2 and 201 not in mgr.g3
        assert mgr.probe_prefix([201, 202, 203], 0) == 3
        got = mgr.lookup(201)
        assert got is not None and 201 in mgr.g2
        np.testing.assert_array_equal(got[0], pages[1][0])
        # a second manager sharing the same object store finds the peer's
        # block through the shared tier (membership falls through to the
        # backend) and onboards it into its own G2
        mgr2 = KvBlockManager(
            BlockManagerConfig(g2_capacity_blocks=2, g4_capacity_blocks=4),
            read_page=lambda pid: pages[pid], write_page=lambda *a: None,
            g4_storage=remote,
        )
        assert mgr2.probe_prefix([201], 0) == 1  # cross-worker membership
        got2 = mgr2.lookup(201)
        assert got2 is not None and 201 in mgr2.g2
        np.testing.assert_array_equal(got2[0], pages[1][0])
        assert "g4" in mgr.stats()
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        loop.close()


def test_g4_capacity_eviction_deletes_remote(tmp_path):
    import asyncio
    import threading

    from dynamo_tpu.blocks.storage import RemoteStorage
    from dynamo_tpu.runtime.discovery import MemoryStore
    from dynamo_tpu.runtime.objects import ObjectStore

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        remote = RemoteStorage(ObjectStore(MemoryStore()), loop)
        from dynamo_tpu.blocks.tier import TierPool

        g4 = TierPool("g4", remote, 2)
        g4.put(1, payload(1))
        g4.put(2, payload(2))
        g4.put(3, payload(3))  # evicts 1
        assert 1 not in g4 and remote.read(1) is None
        assert remote.read(2) is not None
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        loop.close()


# -- engine integration ------------------------------------------------------


def make_core_with_tiers(num_pages, tmp_path=None, engine_kw=None, g4_storage=None, **bm_kw):
    runner = ModelRunner(CFG, PARAMS, num_pages=num_pages, page_size=PAGE,
                         max_batch_size=4, prefill_bucket=16, attn_impl="reference")
    bm_cfg = BlockManagerConfig(**bm_kw) if tmp_path is None else BlockManagerConfig(
        g3_path=tmp_path / "g3", **bm_kw
    )
    bm = KvBlockManager(bm_cfg, read_page=runner.read_page, write_page=runner.write_page,
                        write_pages=getattr(runner, "write_pages", None),
                        g4_storage=g4_storage)
    config = EngineConfig(num_pages=num_pages, page_size=PAGE, max_batch_size=4,
                          max_prefill_tokens=256, max_seq_len=128, **(engine_kw or {}))
    return EngineCore(runner, config, block_manager=bm), bm


def test_onboard_after_g1_eviction():
    # Tiny G1 (6 usable pages) + G2: run prompt A (3 pages), then B to evict
    # A from G1, then A again — it must onboard from G2, not recompute-miss.
    core, bm = make_core_with_tiers(num_pages=7, g2_capacity_blocks=16)
    pa = list(range(1, 13))  # 12 tokens = 3 pages
    pb = [50 + i for i in range(12)]
    core.add_request(greedy_request(pa, max_tokens=2))
    out_a = run_to_completion(core)
    assert bm.offloaded >= 2  # write-through happened

    core.add_request(greedy_request(pb, max_tokens=2))
    run_to_completion(core)

    seq = core.add_request(greedy_request(pa, max_tokens=2))
    out_a2 = run_to_completion(core)
    assert out_a2[seq.seq_id] == out_a[0] == greedy_reference(pa, 2)
    assert bm.onboarded >= 1, "expected G2 onboarding after G1 eviction"
    assert seq.num_cached_at_start >= 4


def test_onboarded_tokens_exact_vs_reference(tmp_path):
    # Cascade all the way to disk: G2 capacity 1 forces G3 use.
    core, bm = make_core_with_tiers(num_pages=7, tmp_path=tmp_path,
                                    g2_capacity_blocks=1, g3_capacity_blocks=16)
    pa = list(range(1, 13))
    core.add_request(greedy_request(pa, max_tokens=3))
    run_to_completion(core)
    # Push A's blocks out of G1 and mostly out of G2.
    for offset in (60, 80):
        core.add_request(greedy_request([offset + i for i in range(12)], max_tokens=2))
        run_to_completion(core)
    seq = core.add_request(greedy_request(pa, max_tokens=3))
    out = run_to_completion(core)
    assert out[seq.seq_id] == greedy_reference(pa, 3)
    assert (bm.g3.stats().hits + bm.g2.stats().hits) >= 1


def test_onboard_batches_through_write_pages():
    """N onboarded pages go through one write_pages scatter, not N
    per-page round-trips (the per-page writer stays the fallback)."""
    batched, single = [], []
    mgr = KvBlockManager(
        BlockManagerConfig(g2_capacity_blocks=8),
        read_page=lambda pid: payload(pid),
        write_page=lambda pid, k, v: single.append(pid),
        write_pages=lambda pids, ks, vs: batched.append((list(pids), len(ks))),
    )
    mgr.onboard([3, 4, 5], [payload(1), payload(2), payload(3)])
    assert batched == [([3, 4, 5], 3)] and single == []
    # A single payload skips the batch machinery (no stacking overhead).
    mgr.onboard([7], [payload(9)])
    assert single == [7] and len(batched) == 1
    assert mgr.onboarded == 4


def test_async_onboard_tokens_exact():
    """Pipelined onboarding (DYN_ASYNC_ONBOARD): the background fetch +
    batched write_pages landing must produce token-exact results, and the
    scheduler must report the onboarded prefix as cached."""
    core, bm = make_core_with_tiers(
        num_pages=7, g2_capacity_blocks=16,
        engine_kw={"async_onboard": True, "chunk_prefill_tokens": 8},
    )
    pa = list(range(1, 13))  # 12 tokens = 3 pages
    core.add_request(greedy_request(pa, max_tokens=2))
    out_a = run_to_completion(core)
    core.add_request(greedy_request([50 + i for i in range(12)], max_tokens=2))
    run_to_completion(core)  # evicts A from tiny G1

    seq = core.add_request(greedy_request(pa, max_tokens=2))
    out_a2 = run_to_completion(core)
    assert out_a2[seq.seq_id] == out_a[0] == greedy_reference(pa, 2)
    assert core.onboard_sessions >= 1, "expected an async onboarding session"
    assert not core._onboards  # every session landed
    assert sum(core.onboard_page_counts.values()) >= 2
    assert bm.onboarded >= 2
    assert seq.num_cached_at_start >= 4
    assert core.onboard_wait_count >= 1
    assert len(core.drain_onboard_waits()) >= 1
    assert core.drain_onboard_waits() == []  # drained exactly once


def test_async_onboard_probe_fetch_race_recomputes():
    """Blocks lost between probe and the async fetch (here: a metadata-only
    G2 whose payload reads always come up empty) must degrade to recompute
    with token-exact output — the shortfall path of the pipelined session."""
    core, _bm = make_core_with_tiers(
        num_pages=7, g2_capacity_blocks=16, null_storage=True,
        engine_kw={"async_onboard": True, "chunk_prefill_tokens": 8},
    )
    pa = list(range(1, 13))
    core.add_request(greedy_request(pa, max_tokens=2))
    run_to_completion(core)
    core.add_request(greedy_request([50 + i for i in range(12)], max_tokens=2))
    run_to_completion(core)

    seq = core.add_request(greedy_request(pa, max_tokens=2))
    out = run_to_completion(core)
    assert out[seq.seq_id] == greedy_reference(pa, 2)
    assert core.onboard_sessions >= 1
    assert core.onboard_shortfall_pages >= 1, "probe hit but fetch lost: shortfall"
    assert seq.status.value == "finished" and seq.onboard_pending == 0


def test_async_onboard_chaos_store_fault_recomputes(tmp_path):
    """Chaos drill: a store.op fault fired during the background G4 fetch
    must degrade the session to recompute (token-exact), never crash the
    engine thread."""
    import asyncio
    import threading

    from dynamo_tpu.blocks.storage import RemoteStorage
    from dynamo_tpu.runtime.faults import FAULTS
    from dynamo_tpu.runtime.objects import ObjectStore
    from dynamo_tpu.runtime.store_server import StoreClient, StoreServer

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        async def _bring_up():
            server = await StoreServer(host="127.0.0.1", port=0).start()
            return server, StoreClient("127.0.0.1", server.port)

        server, client = asyncio.run_coroutine_threadsafe(_bring_up(), loop).result(10)
        remote = RemoteStorage(ObjectStore(client), loop)
        # G2 capacity 1 + no G3: committed blocks spill host -> remote, so
        # the replay's onboard fetch must cross the faulted store plane.
        core, bm = make_core_with_tiers(
            num_pages=7, g2_capacity_blocks=1, g4_capacity_blocks=16,
            g4_storage=remote,
            engine_kw={"async_onboard": True, "chunk_prefill_tokens": 8},
        )
        pa = list(range(1, 13))
        core.add_request(greedy_request(pa, max_tokens=2))
        run_to_completion(core)
        core.add_request(greedy_request([50 + i for i in range(12)], max_tokens=2))
        run_to_completion(core)
        assert bm.g4 is not None and bm.g4.stats().used >= 1

        FAULTS.arm("store.op:drop@1")
        try:
            seq = core.add_request(greedy_request(pa, max_tokens=2))
            out = run_to_completion(core)
            assert FAULTS.fired("store.op") >= 1, "fault never crossed the fetch path"
        finally:
            FAULTS.disarm()
        assert out[seq.seq_id] == greedy_reference(pa, 2)
        assert not core._onboards

        async def _tear_down():
            await client.close()
            await server.close()

        asyncio.run_coroutine_threadsafe(_tear_down(), loop).result(10)
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)
        loop.close()
