"""JSON-constrained decoding: char machine, token masks, engine + HTTP."""

import json

import numpy as np
import pytest

from dynamo_tpu.constrained import (
    MachineState,
    TokenMaskCache,
    advance_text,
)


def _ok(text):
    return advance_text(MachineState(), text).mode != "X"


def _complete(text):
    s = advance_text(MachineState(), text)
    return s.mode != "X" and s.complete()


def test_json_prefix_machine():
    # Valid prefixes of valid JSON.
    for t in ['{', '{"a"', '{"a": [1, 2', '{"a": {"b": "c\\n', '[', '[[',
              '-12.5e', 'tru', '"x', '  {"k": nul', '[1, {"a": true}',
              '{"a": 1, "b']:
        assert _ok(t), t
    # Complete values.
    for t in ['{}', '[]', '{"a": 1}', '[1, 2, 3]', '"hi"', 'true', 'null',
              '-3.5e2', '{"a": {"b": []}}', ' { "a" : "b" } ']:
        assert _complete(t), t
    # Invalid.
    for t in ['}', '{]', '{"a" 1}', '{,', '[1 2]', '{"a": }', 'trux',
              '{"a": "b"} x', '{1: 2}', '{"a"}']:
        assert not _ok(t), t
    # Valid prefix but NOT complete.
    for t in ['{', '{"a": 1', '[1,', '"open', 'fal']:
        s = advance_text(MachineState(), t)
        assert s.mode != "X" and not s.complete(), t


def test_string_escapes_match_rfc8259():
    """STR_ESCAPE admits exactly \\" \\\\ \\/ \\b \\f \\n \\r \\t \\u; \\u
    consumes exactly 4 hex digits (ADVICE: '\\q' / '\\u12"' used to be
    accepted, then json.loads rejected the 'guaranteed' output)."""
    # Every legal escape, including \u with exactly 4 hex digits.
    for t in ['"\\n"', '"\\""', '"\\\\"', '"\\/"', '"\\b"', '"\\f"',
              '"\\r"', '"\\t"', '"\\u0041"', '"\\uBEEF"', '"a\\u00e9b"']:
        assert _complete(t), t
        json.loads(t)
    # Prefixes mid-escape stay valid prefixes.
    for t in ['"\\', '"\\u', '"\\u1', '"\\u12', '"\\u123', '{"k\\u00']:
        assert _ok(t), t
    # Illegal escape chars, and \u with a non-hex digit or an early quote.
    for t in ['"\\q', '"\\x41"', '"\\8"', '"\\uZ', '"\\u12G', '"\\u12"',
              '"\\u123"', '"\\u"']:
        assert not _ok(t), t


def test_budget_to_close_is_true_upper_bound():
    """budget_to_close must dominate the force-close walk's step count —
    including pending \\u hex digits and the ':'+value cost of an open KEY
    string (it used to say 1 for '{\"k' and the close became unaffordable)."""
    tok = _CharTok()
    cache = TokenMaskCache(tok, vocab_size=len(tok.CHARS), eos_ids=(0,))
    for prefix in ['{"k', '{"k\\', '{"k\\u00', '"v\\u0', '{"a": {"b',
                   '{"a": [1, "s\\u1', '{"k": "v"']:
        s = advance_text(MachineState(), prefix)
        assert s.mode != "X", prefix
        budget = cache.budget_to_close(s)
        text, steps = prefix, 0
        while not s.complete():
            mask = cache.mask_for(s, force_close=True)
            tid = int(np.nonzero(mask)[0][0])
            assert tid != 0, (prefix, text)
            text += tok.CHARS[tid]
            s = advance_text(s, tok.CHARS[tid])
            steps += 1
            assert steps <= budget, (prefix, text, budget)
        json.loads(text)
        assert steps + 1 <= budget, (prefix, steps, budget)  # +1 spare for EOS


class _CharTok:
    """1 token = 1 char over a tiny charset (plus an EOS at id 0)."""

    CHARS = '\x00{}[]",:0123456789.-eE tfalsrunx\\n"'

    def decode(self, ids, skip_special_tokens=True):
        return "".join(self.CHARS[i] if 0 < i < len(self.CHARS) else "" for i in ids)


def test_token_masks_allow_exactly_valid_continuations():
    tok = _CharTok()
    cache = TokenMaskCache(tok, vocab_size=len(tok.CHARS), eos_ids=(0,))
    s = advance_text(MachineState(), '{"a"')
    mask = cache.mask_for(s)  # AFTER_KEY: only ':' (and whitespace)
    allowed = {tok.CHARS[i] for i in np.nonzero(mask)[0]}
    assert ":" in allowed and "}" not in allowed and "5" not in allowed
    # EOS only when complete.
    assert not mask[0]
    done = advance_text(MachineState(), '{"a": 1}')
    assert cache.mask_for(done)[0]
    # Cache: same summary -> same array object base (hit path).
    assert cache.mask_for(s) is not None and len(cache._masks) >= 1


def test_force_close_terminates_any_state():
    tok = _CharTok()
    cache = TokenMaskCache(tok, vocab_size=len(tok.CHARS), eos_ids=(0,))
    for prefix in ['{"a": [1, {"b": "x', '{"a"', '[', '[[[', 'tr', '{"k": ']:
        s = advance_text(MachineState(), prefix)
        text = prefix
        for _ in range(40):
            if s.complete():
                break
            mask = cache.mask_for(s, force_close=True)
            tid = int(np.nonzero(mask)[0][0])
            if tid == 0:
                break
            text += tok.CHARS[tid]
            s = advance_text(s, tok.CHARS[tid])
        assert s.complete(), (prefix, text)
        json.loads(text)


def test_token_masks_with_multichar_bpe_pieces():
    """Real-vocab shape: multi-char pieces ('{\"', '\": ', 'true', '1,'),
    pieces that open/close several levels, and junk. The machine simulates
    pieces char-by-char, so a piece is allowed iff the whole piece keeps a
    valid prefix."""
    class _Tok:
        PIECES = ["", '{"', '": ', "true", "1,", "}}", "[[", '{"a": 1}',
                  "xy", ", \"", "null}", " 42", '"k', 'literal trap']

        def decode(self, ids, skip_special_tokens=True):
            return "".join(self.PIECES[i] for i in ids if i < len(self.PIECES))

    tok = _Tok()
    cache = TokenMaskCache(tok, len(tok.PIECES), eos_ids=())
    p = tok.PIECES

    start = MachineState()
    m = cache.mask_for(start)
    allowed = {p[i] for i in np.nonzero(m)[0]}
    assert '{"' in allowed and "true" in allowed and '{"a": 1}' in allowed
    assert " 42" in allowed and "[[" in allowed
    # '": ' IS allowed at start: '"' opens a string, ': ' is content.
    assert "}}" not in allowed and "xy" not in allowed

    after_key = advance_text(start, '{"a"')
    m2 = cache.mask_for(after_key)
    allowed2 = {p[i] for i in np.nonzero(m2)[0]}
    assert '": ' not in allowed2  # we're past the key's closing quote
    assert "xy" not in allowed2 and "true" not in allowed2
    after_colon = advance_text(start, '{"a": ')
    m3 = cache.mask_for(after_colon)
    allowed3 = {p[i] for i in np.nonzero(m3)[0]}
    assert "true" in allowed3 and " 42" in allowed3 and '{"' in allowed3
    assert "null}" in allowed3  # value + close in one piece
    assert "1," in allowed3  # number then ',' -> EXPECT_KEY: valid prefix
    # Deep-close soundness: '}}' from depth-2 object is fine...
    deep = advance_text(start, '{"a": {"b": 1')
    m4 = cache.mask_for(deep)
    assert m4[p.index("}}")]
    # ...and multi-open pieces respect the remaining-budget filter.
    tight = cache.mask_for(start, remaining=3)
    assert not tight[p.index("[[")]  # 2 opens can't close in 2 tokens
    assert tight[p.index('{"a": 1}')] or tight[p.index("true")]


# ---------------------------------------------------------------------------
# Vectorized mask builder: bitwise parity with the per-char Python walk
# ---------------------------------------------------------------------------

_PARITY_PIECES = [
    "", "{", "}", "[", "]", ",", ":", '"', " ", "  ", "\t", "\n",
    "0", "1", "9", "-", "-1", "12", "1.5", "0.25", "1e9", "1E+3", "1e-",
    "01", "0.", ".", "e", "E", "+", "-5e2", "123,", "1, ", "3]", "4}",
    "true", "false", "null", "t", "tr", "rue", "alse", "ull", "n", "f",
    '"a"', '"ab', "abc", "a b", "\\", "\\n", "\\u", "\\u0041", "u00", "0041",
    '"key":', '": ', '","', '"}', '"]', '"},"', '":', "k", "\x00", "\x01",
    '{"', "[1", "[[", "{{", "[]", "{}", "[1,2]", '{"a":1}', "}]", "]]",
    "}}", "],", "},", ',"', ', "', " ]", " }", "��", "�", "٣", "²",
    '"٣"', "hello", 'wor"ld', '\\"', "\\\\", "/", "b", "r",
    '"a":', "1}", "2]", "e5", ".5", "5.", "+7", "-0", "-0.5e+10", "�]",
]

_PARITY_STATES = [
    "", "{", '{"', '{"k', '{"k"', '{"k":', '{"k": ', '{"k": 1',
    '{"k": 1,', '{"k": 1, ', '{"k": "v"', '{"k": "v",', '{"k": [',
    '{"k": [1', '{"k": [1,', '{"k": [1,2', '{"k": [1,2]', '{"k": tr',
    '{"k": -', '{"k": 0', '{"k": 1.', '{"k": 1.5', '{"k": 1e',
    '{"k": 1e+', '{"k": 1e+3', '{"k": "a\\', '{"k": "a\\u', '{"k": "a\\u0',
    '{"k": "a\\u00', '{"k": "a\\u004', "[", "[[", "[[[", "[[[[", "[[[[[",
    "[[[[[1", "[[[[[1,", "[{", '[{"a": [', '[{"a": [[', "[1, ", "[tru",
    "[fals", "[nul", "1", "-", "0", "[0", '{"a": {"b": {"c": {"d": ',
    '{"a": {"b": {"c": {"d": 1', '{"a": {"b": {"c": {"d": 1}',
    '{"a": {"b": {"c": {"d": 1}}', '[[[[{"x": ', '[[{"x": "y"',
    "[ ", "{ ", "[1 ", '"s', '"s\\', '"', "tru", "12345", "-1.5e",
]


class _PieceTok:
    def decode(self, ids, skip_special_tokens=True):
        return "".join(_PARITY_PIECES[i] for i in ids if i < len(_PARITY_PIECES))


def test_vectorized_masks_bitwise_match_python():
    """The vectorized builder must be BITWISE identical to the per-char
    Python walk — allow mask, close_after budgets, descriptor ids and
    decoded descriptor tuples — across pathological pieces (NUL, lone
    replacement chars, non-ASCII Unicode digits like '٣' which count as
    digits in number phases but not as number STARTS, multi-open/close
    pieces) and a state corpus touching every machine mode and depth>3."""
    from dynamo_tpu import constrained as C

    states, seen = [MachineState()], set()
    for text in _PARITY_STATES:
        s = advance_text(MachineState(), text)
        if s.mode != C.REJECT:
            states.append(s)
    checked = 0
    for st in states:
        key = st.summary()
        if key in seen:
            continue
        seen.add(key)
        cache_v = TokenMaskCache(_PieceTok(), len(_PARITY_PIECES), (0,))
        cache_p = TokenMaskCache(_PieceTok(), len(_PARITY_PIECES), (0,))
        pieces = cache_v._ensure_pieces()
        cache_p._ensure_pieces()
        av, cv = cache_v._build_mask_vectorized(st, key, pieces)
        ap, cp = cache_p._build_mask_python(st, key, pieces)
        np.testing.assert_array_equal(av, ap, err_msg=f"allow mask @ {key}")
        np.testing.assert_array_equal(cv, cp, err_msg=f"close_after @ {key}")
        dv, descv = cache_v._descs[key]
        dp, descp = cache_p._descs[key]
        np.testing.assert_array_equal(dv, dp, err_msg=f"desc ids @ {key}")
        assert descv == descp, key
        checked += 1
    assert checked > 40  # corpus actually covered distinct summaries


def test_vector_masks_env_fallback(monkeypatch):
    """DYN_CONSTRAINT_VECTOR_MASKS=0 routes mask_for through the Python
    builder and yields the same masks."""
    tok = _CharTok()
    s = advance_text(MachineState(), '{"a": [1, ')
    a = TokenMaskCache(tok, len(tok.CHARS), (0,)).mask_for(s)
    monkeypatch.setenv("DYN_CONSTRAINT_VECTOR_MASKS", "0")
    b = TokenMaskCache(tok, len(tok.CHARS), (0,)).mask_for(s)
    np.testing.assert_array_equal(a, b)


def test_mask_build_timing_drained():
    """Cold builds record wall-time samples; drain returns-and-clears (the
    metrics exporter feeds dynamo_engine_constraint_mask_build_seconds)."""
    tok = _CharTok()
    cache = TokenMaskCache(tok, vocab_size=len(tok.CHARS), eos_ids=(0,))
    cache.mask_for(advance_text(MachineState(), '{"a": '))
    cache.mask_for(advance_text(MachineState(), '{"a": '))  # warm: no build
    samples = cache.drain_build_seconds()
    assert len(samples) == 1 and samples[0] >= 0.0
    assert cache.drain_build_seconds() == []


def test_engine_json_mode_yields_parseable_json():
    """Greedy generation on a RANDOM tiny model, json_mode on: the output
    must parse (force-close kicks in before max_tokens)."""
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.tokenizer import ByteTokenizer

    cfg = PRESETS["test-tiny"]
    runner = ModelRunner(cfg, llama.init_params(cfg, 0), num_pages=64, page_size=4,
                         max_batch_size=2, prefill_bucket=16, attn_impl="reference")
    core = EngineCore(runner, EngineConfig(
        num_pages=64, page_size=4, max_batch_size=2,
        max_prefill_tokens=64, max_seq_len=128, decode_steps=4,
    ))
    tok = ByteTokenizer()
    core.set_constraint_tokenizer(tok)
    for seed, max_tokens in [(1, 24), (2, 48)]:
        seq = core.add_request(PreprocessedRequest(
            token_ids=tok.encode("data: ", add_bos=False),
            sampling=SamplingOptions(temperature=0.8, seed=seed, json_mode=True),
            stop=StopConditions(max_tokens=max_tokens),
        ), Context())
        toks = []
        while core.has_work:
            for s, out in core.step():
                if s is seq:
                    toks.extend(out.token_ids)
        text = tok.decode([t for t in toks if t not in core._eos])
        parsed = json.loads(text)  # must be COMPLETE valid JSON
        assert parsed is None or isinstance(parsed, (dict, list, str, int, float, bool))


@pytest.mark.e2e
async def test_json_mode_served_http():
    """response_format json_object over the full HTTP stack."""
    import aiohttp

    from dynamo_tpu.launch import run_local

    handles = await run_local("test-tiny", port=0, num_pages=256, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "max_tokens": 40, "temperature": 0.7,
                    "seed": 5, "response_format": {"type": "json_object"},
                    "messages": [{"role": "user", "content": "give me json"}]}
            r = await (await s.post(base + "/v1/chat/completions", json=body)).json()
            content = r["choices"][0]["message"]["content"]
            json.loads(content)
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
