"""SentencePiece .model support: protobuf round-trip, unigram + BPE encode,
control-token handling, dir resolution through load_tokenizer.

The writer serializes a real ModelProto (wire-format), so parsing it back
exercises the same decode path a llama/mistral tokenizer.model hits.
"""

import pytest

from dynamo_tpu.sentencepiece import (
    BYTE,
    CONTROL,
    NORMAL,
    UNKNOWN,
    ProtoError,
    SentencePieceModel,
    build_tokenizer,
    load_sentencepiece,
    write_model,
)

UNI_PIECES = [
    ("<unk>", 0.0, UNKNOWN),
    ("<s>", 0.0, CONTROL),
    ("</s>", 0.0, CONTROL),
    ("▁hello", -1.0, NORMAL),
    ("▁world", -1.5, NORMAL),
    ("▁", -2.0, NORMAL),
    ("hell", -3.0, NORMAL),
    ("o", -3.5, NORMAL),
]


def test_proto_roundtrip(tmp_path):
    raw = write_model(UNI_PIECES)
    m = SentencePieceModel(raw)
    assert [p[0] for p in m.pieces] == [p[0] for p in UNI_PIECES]
    assert m.pieces[3][1] == pytest.approx(-1.0)
    assert m.pieces[1][2] == CONTROL
    assert m.unk_id == 0 and m.bos_id == 1 and m.eos_id == 2
    assert m.add_dummy_prefix


def test_unigram_encode_decode(tmp_path):
    path = tmp_path / "tokenizer.model"
    path.write_bytes(write_model(UNI_PIECES))
    tok = load_sentencepiece(path)
    ids = tok.encode("hello world")
    assert ids == [3, 4]
    assert tok.decode(ids) == "hello world"
    # control tokens skipped on decode, bos honored
    assert tok.decode([1, 3, 4, 2]) == "hello world"
    assert tok.encode("hello world", add_bos=True)[0] == 1
    assert 2 in tok.eos_token_ids


def test_bpe_model_with_merges(tmp_path):
    pieces = [
        ("<unk>", 0.0, UNKNOWN),
        ("<s>", 0.0, CONTROL),
        ("</s>", 0.0, CONTROL),
        ("▁", -1.0, NORMAL),
        ("a", -1.1, NORMAL),
        ("b", -1.2, NORMAL),
        ("ab", -0.5, NORMAL),
        ("▁ab", -0.4, NORMAL),
    ]
    path = tmp_path / "tokenizer.model"
    path.write_bytes(write_model(pieces, model_type="bpe"))
    tok = load_sentencepiece(path)
    ids = tok.encode("ab")
    assert ids == [7]  # ▁ + ab merged up to ▁ab
    assert tok.decode(ids) == "ab"
    assert tok.decode(tok.encode("ab ab")) == "ab ab"


def test_byte_fallback_unigram(tmp_path):
    pieces = list(UNI_PIECES) + [(f"<0x{i:02X}>", -10.0, BYTE) for i in range(256)]
    path = tmp_path / "tokenizer.model"
    path.write_bytes(write_model(pieces))
    tok = load_sentencepiece(path)
    # 'Zürich' has no pieces: must round-trip through byte fallback
    assert tok.decode(tok.encode("hello Zürich")).strip() == "hello Zürich"


def test_dir_resolution_prefers_json_falls_back_to_model(tmp_path):
    from dynamo_tpu.tokenizer import load_tokenizer

    (tmp_path / "tokenizer.model").write_bytes(write_model(UNI_PIECES))
    tok = load_tokenizer(tmp_path)
    assert tok.decode(tok.encode("hello world")) == "hello world"


def test_truncated_proto_raises():
    with pytest.raises(ProtoError):
        SentencePieceModel(write_model(UNI_PIECES)[:-3])
    with pytest.raises(ProtoError, match="no pieces"):
        SentencePieceModel(b"")
