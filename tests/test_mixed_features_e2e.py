"""Mixed-feature serving: text, logprobs, json_mode, and penalized requests
CONCURRENTLY against one stack. These features each force different decode
paths (pipelined bursts vs sync single-step with masks/aux), and the
engine switches per batch composition — this pins the interplay: nobody's
output corrupts anybody else's, and every contract holds simultaneously.
"""

import asyncio
import json

import pytest


@pytest.mark.e2e
async def test_mixed_feature_traffic_one_stack():
    import aiohttp

    from tests.conftest import start_stack, stop_stack

    handles, base = await start_stack(num_pages=512)

    async def post(s, body):
        async with s.post(base + "/v1/chat/completions", json=body) as r:
            assert r.status == 200, await r.text()
            return await r.json()

    def msg(text):
        return [{"role": "user", "content": text}]

    try:
        async with aiohttp.ClientSession() as s:
            # Baseline: the same text request alone, for interference checks.
            plain_body = {"model": "test-tiny", "max_tokens": 12, "temperature": 0,
                          "messages": msg("hello there")}
            baseline = (await post(s, plain_body))["choices"][0]["message"]["content"]

            jobs = [
                post(s, dict(plain_body)),
                post(s, {"model": "test-tiny", "max_tokens": 10, "temperature": 0,
                         "logprobs": True, "top_logprobs": 3,
                         "messages": msg("with logprobs")}),
                post(s, {"model": "test-tiny", "max_tokens": 30, "temperature": 1.1,
                         "seed": 7, "response_format": {"type": "json_object"},
                         "messages": msg("json now")}),
                post(s, {"model": "test-tiny", "max_tokens": 10, "temperature": 0.5,
                         "seed": 3, "frequency_penalty": 0.8,
                         "messages": msg("penalized")}),
                post(s, {"model": "test-tiny", "max_tokens": 8, "temperature": 0,
                         "logprobs": True, "top_logprobs": 0,
                         "response_format": {"type": "json_object"},
                         "messages": msg("json AND logprobs")}),
            ]
            plain, lp, js, pen, combo = await asyncio.gather(*jobs)

            # Text neighbor unchanged by the zoo around it.
            assert plain["choices"][0]["message"]["content"] == baseline

            content = lp["choices"][0]["logprobs"]["content"]
            assert len(content) == 10
            assert all(len(e["top_logprobs"]) == 3 for e in content)

            json.loads(js["choices"][0]["message"]["content"])

            assert pen["usage"]["completion_tokens"] == 10

            # Combined json_mode + logprobs: both contracts at once.
            json.loads(combo["choices"][0]["message"]["content"])
            centries = combo["choices"][0]["logprobs"]["content"]
            assert len(centries) == combo["usage"]["completion_tokens"]
            assert all(e["top_logprobs"] == [] for e in centries)
    finally:
        await stop_stack(handles)
