"""Two-process multi-host bring-up: store rendezvous -> jax.distributed ->
one global CPU mesh serving a sharded model (see _multihost_child.py).

This is the CPU-mesh stand-in for a TPU pod slice: each child process owns 4
virtual devices; after bring-up both hold the same 8-device global mesh and
produce logits identical to single-device execution.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_sharded_forward(tmp_path):
    store_port, coord_port = _free_port(), _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # children pin their own device count (4)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(rank: int):
        return subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "_multihost_child.py"),
             str(rank), str(store_port), str(coord_port)],
            env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    p0, p1 = spawn(0), spawn(1)
    out0, _ = p0.communicate(timeout=300)
    out1, _ = p1.communicate(timeout=300)
    assert p0.returncode == 0, f"rank0:\n{out0}\nrank1:\n{out1}"
    assert p1.returncode == 0, f"rank1:\n{out1}"
    assert "MH_OK rank=0 devices=8" in out0
    assert "MH_OK rank=1 devices=8" in out1
