"""Fast-tier stall-free invariant: the engine never runs a prefill-only
step while decodable sequences are running (ISSUE 2 CI guard).

Uses a stub runner (no jit, no model) so the scheduler's dispatch
composition is observable directly: every dispatch records its per-row
token counts, and ``EngineCore.last_step_info`` / ``stall_violations``
expose what the step carried. A future scheduler refactor that silently
reintroduces the prefill-XOR-decode behavior fails here in milliseconds.
"""

import numpy as np

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

PAGE = 4


class StubCfg:
    vocab_size = 128
    image_token_id = None
    video_token_id = None
    mrope_section = None


class StubRunner:
    """Minimal ModelRunner stand-in: returns a fixed token for every row and
    records each dispatch's per-row new-token counts."""

    def __init__(self, num_pages=64, page_size=PAGE):
        self.num_pages = num_pages
        self.page_size = page_size
        self.cfg = StubCfg()
        self.dispatches: list[np.ndarray | None] = []  # num_new per dispatch

    def step(self, batch, lp_k=0):
        self.dispatches.append(None if batch.num_new is None
                               else np.asarray(batch.num_new))
        b = batch.tokens.shape[0]
        toks = np.full(b, 7, np.int32)
        if lp_k:
            zeros = np.zeros((b,), np.float32)
            return toks, (zeros, np.zeros((b, lp_k), np.int32),
                          np.zeros((b, lp_k), np.float32))
        return toks


def make_core(chunk, num_pages=64, max_batch=8, max_prefill=256, **cfg_kw):
    runner = StubRunner(num_pages=num_pages)
    return EngineCore(runner, EngineConfig(
        num_pages=num_pages, page_size=PAGE, max_batch_size=max_batch,
        max_prefill_tokens=max_prefill, max_seq_len=256,
        chunk_prefill_tokens=chunk, enable_prefix_caching=False, **cfg_kw,
    ))


def req(n_prompt, max_tokens=8, start=1):
    return PreprocessedRequest(
        token_ids=list(range(start, start + n_prompt)),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )


def drive(core, inject=(), max_steps=500, check=True):
    """Step to completion, injecting (at_step, request) pairs; after every
    step assert the stall-free invariant via last_step_info."""
    pending = sorted(inject, key=lambda x: x[0], reverse=True)
    for i in range(max_steps):
        if not core.has_work and not pending:
            return i
        while pending and pending[-1][0] <= i:
            core.add_request(pending.pop()[1])
        info = dict(core.last_step_info)
        core.step()
        if check and core.last_step_info != info:  # dispatched mixed work
            got = core.last_step_info
            if got["chunk_rows"] and got["decodable"]:
                assert got["decode_rows"] == got["decodable"], (
                    f"step {i}: prefill chunks dispatched without the "
                    f"running decodes: {got}"
                )
    raise AssertionError("engine did not drain")


def test_stall_free_invariant_under_long_prefill():
    """Decodes running + a long prompt arriving: every dispatch that carries
    prefill chunks must also carry every decodable row."""
    core = make_core(chunk=4)
    for i in range(3):
        core.add_request(req(5, max_tokens=30, start=10 * i + 1))
    drive(core, inject=[(6, req(60, max_tokens=4, start=60))])
    assert core.mixed_steps > 0
    assert core.stall_violations == 0


def test_legacy_xor_mode_counts_violations():
    """chunk_prefill_tokens=0 restores phase-exclusive steps — and the
    violation counter proves the probe can see the difference."""
    core = make_core(chunk=0)
    for i in range(3):
        core.add_request(req(5, max_tokens=30, start=10 * i + 1))
    drive(core, inject=[(6, req(60, max_tokens=4, start=60))], check=False)
    assert core.mixed_steps == 0
    assert core.stall_violations > 0


def test_chunks_respect_budget_while_decoding():
    """With decodes running, no dispatch row computes more than the chunk
    budget; decode rows are always exactly 1 token."""
    chunk = 4
    core = make_core(chunk=chunk)
    core.add_request(req(5, max_tokens=40))
    drive(core, inject=[(3, req(57, max_tokens=2, start=100))])
    mixed = [d for d in core.runner.dispatches if d is not None and len(d) > 1]
    assert mixed, "scenario must produce fused dispatches"
    for d in mixed:
        assert d.max() <= chunk


def test_head_of_line_incremental_admission():
    """A prompt needing more pages than are currently free must admit
    incrementally as pages free up — not park at waiting[0] forever (the
    HOL fix) and not wedge the engine."""
    # 15 usable pages (page 0 is reserved); the decoder holds ~4 and the
    # 48-token prompt needs 12 at once — it can never have all 12 while
    # the decoder lives, so only chunked admission can start it.
    core = make_core(chunk=4, num_pages=16, max_batch=4)
    core.add_request(req(8, max_tokens=6))
    big = core.add_request(req(48, max_tokens=2, start=100))
    started_while_short_ran = False
    for _ in range(200):
        if not core.has_work:
            break
        core.step()
        if core.prefilling and any(not s.is_finished for s in [big]):
            if any(s.num_generated < 6 and s is not big for s in core.running):
                started_while_short_ran = True
    assert big.is_finished and big.finish_reason is not None
    assert big.finish_reason.value == "length"
    assert started_while_short_ran, "big prompt should start before the pool is idle"


def test_never_fitting_prompt_rejected_not_wedged():
    """A prompt that can never fit the page pool is rejected with an error
    finish instead of wedging the queue head."""
    core = make_core(chunk=4, num_pages=8, max_batch=4)
    seq = core.add_request(req(200, max_tokens=2))
    assert seq.is_finished
    # Engine still serves others.
    ok = core.add_request(req(5, max_tokens=3))
    for _ in range(50):
        if not core.has_work:
            break
        core.step()
    assert ok.is_finished and ok.finish_reason.value == "length"


def test_mid_prompt_sequence_not_decodable():
    """A sequence mid-chunk must never appear in a decode batch: its rows
    always come in via chunk scheduling (num_new set), and it only joins
    running after its final chunk."""
    core = make_core(chunk=4)
    seq = core.add_request(req(19, max_tokens=3))
    while core.prefilling or core.waiting:
        assert seq not in core.running
        core.step()
    assert seq in core.running or seq.is_finished
    assert seq.num_cached >= 19
