"""Real-checkpoint serving smoke: content asserts, not logits.

``tests/fixtures/smoke-q4k.gguf`` is a REAL checkpoint in every dimension
the serving stack exercises (built by ``tools/make_smoke_gguf.py``): a
genuine BPE tokenizer embedded GGUF-style, weights trained until the model
memorizes its corpus, stored in llama.cpp's Q4_K superblock format. That
makes CONTENT assertions possible — prompt with a corpus prefix, assert
the continuation text — through the full HTTP stack: GGUF parse, Q4_K
dequant, embedded-tokenizer reconstruction, prefill, greedy decode,
incremental detokenization. The reference asserts served content the same
way (`tests/serve/test_dynamo_serve.py:94-317`); VERDICT r3 item 10.
"""

import pathlib

import pytest

aiohttp = pytest.importorskip("aiohttp")

FIXTURE = pathlib.Path(__file__).parent / "fixtures" / "smoke-q4k.gguf"
PROMPT = "the quick brown fox"
EXPECTED = " jumps over the lazy dog"


def test_fixture_is_a_real_kquant_gguf():
    from dynamo_tpu.models.gguf import GGML_Q4_K, GGUFReader, tokenizer_from_gguf

    r = GGUFReader(FIXTURE)
    try:
        q4k = [n for n, i in r.tensors.items() if i.ggml_type == GGML_Q4_K]
        assert len(q4k) >= 10, q4k  # matmul weights are K-quantized
        tk = tokenizer_from_gguf(r)
        # Real tokenizer round-trip (multi-token BPE, not byte fallback).
        ids = tk.encode(PROMPT)
        assert 1 < len(ids) < len(PROMPT)
        assert tk.decode(ids) == PROMPT
    finally:
        pass  # shared mmap; GGUFReader closes on GC


@pytest.mark.e2e
async def test_served_content_matches_training_corpus():
    from dynamo_tpu.launch import run_local

    handles = await run_local(str(FIXTURE), port=0, num_pages=64, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    name = FIXTURE.stem
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": name, "prompt": PROMPT, "max_tokens": 8, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
        text = out["choices"][0]["text"]
        # The memorized continuation, through Q4_K weights + the real
        # tokenizer's incremental detokenization.
        assert text.startswith(EXPECTED), repr(text)

        # Determinism across requests (greedy).
        async with aiohttp.ClientSession() as s:
            async with s.post(base + "/v1/completions", json=body) as r:
                out2 = await r.json()
        assert out2["choices"][0]["text"] == text
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
