"""Child process for tests/test_multihost.py: one node of a 2-host worker.

Rank 0 serves the discovery store and leads the barrier; rank 1 joins via
StoreClient. After bring-up both ranks hold one global 8-device CPU mesh
(4 virtual devices per process), run the same sharded forward, and compare
against a locally-computed single-device reference.
"""

import os
import sys

RANK = int(sys.argv[1])
STORE_PORT = int(sys.argv[2])
COORD_PORT = int(sys.argv[3])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


async def main() -> None:
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS
    from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh
    from dynamo_tpu.parallel.multihost import MultiNodeConfig, bringup
    from dynamo_tpu.parallel.sharding import param_shardings
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.tcp import TcpTransport

    if RANK == 0:
        from dynamo_tpu.runtime.store_server import StoreServer

        server = await StoreServer(host="127.0.0.1", port=STORE_PORT).start()
        store = server.store
    else:
        from dynamo_tpu.runtime.store_server import StoreClient

        # The leader's store may not be listening yet: wait for the port.
        deadline = asyncio.get_event_loop().time() + 60
        while True:
            try:
                _r, _w = await asyncio.open_connection("127.0.0.1", STORE_PORT)
                _w.close()
                break
            except OSError:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.2)
        store = StoreClient.from_url(f"tcp://127.0.0.1:{STORE_PORT}")
    runtime = DistributedRuntime(store, TcpTransport(host="127.0.0.1"))

    cfg = MultiNodeConfig(
        num_nodes=2, node_rank=RANK,
        leader_addr=f"127.0.0.1:{COORD_PORT}" if RANK == 0 else None,
    )
    # Leader pins its coordinator port and publishes it through the barrier;
    # the follower discovers it from the store (leader_addr=None).
    addr = await bringup(cfg, runtime)
    assert addr is not None
    devs = jax.devices()
    assert len(devs) == 8, f"rank {RANK}: expected 8 global devices, got {len(devs)}"

    model = PRESETS["test-tiny"]
    params = llama.init_params(model, 0)
    mesh = make_mesh(MeshPlan(dp=2, tp=2, sp=2), devs)
    placed = jax.tree.map(jax.device_put, params, param_shardings(mesh, params))

    b, t, ps = 2, 8, 4
    tokens = jnp.asarray(np.arange(b * t).reshape(b, t) % model.vocab_size, jnp.int32)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None], (b, 1))
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    slots = jnp.take_along_axis(tables, positions // ps, axis=1) * ps + positions % ps
    last = jnp.full((b,), t - 1, jnp.int32)

    def fwd(p):
        kc, vc = llama.init_kv_cache(model, num_pages=8, page_size=ps)
        logits, _, _ = llama.forward(
            p, model, tokens, positions, kc, vc, tables, slots, last,
            attn_impl="reference",
        )
        return logits

    want = np.asarray(fwd(params))  # local single-device reference
    got_fn = jax.jit(fwd, out_shardings=NamedSharding(mesh, P()))
    got = np.asarray(got_fn(placed))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    print(f"MH_OK rank={RANK} devices={len(devs)}", flush=True)
    await runtime.close()


asyncio.run(main())
