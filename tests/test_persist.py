"""Store persistence: WAL replay, lease-key exclusion, compaction, restart
survival through a real store server, and group-commit fsync coalescing.
"""

import asyncio
import json
import os

from dynamo_tpu.runtime.persist import PersistentStore
from dynamo_tpu.runtime.store_server import StoreClient, StoreServer


async def test_wal_roundtrip_and_compaction(tmp_path):
    wal = tmp_path / "store.wal"
    s1 = await PersistentStore.open(wal)
    await s1.put("deployments/a", b"v1")
    await s1.put("deployments/a", b"v2")  # overwrite
    await s1.put("deployments/b", b"x")
    await s1.delete("deployments/b")
    await s1.put("objects/o/meta", b"{}")
    s1.close_log()
    assert len(wal.read_text().splitlines()) == 5  # raw WAL: every mutation

    s2 = await PersistentStore.open(wal)
    assert await s2.get("deployments/a") == b"v2"
    assert await s2.get("deployments/b") is None
    assert await s2.get("objects/o/meta") == b"{}"
    # compaction: one put per surviving key
    assert len(wal.read_text().splitlines()) == 2
    s2.close_log()


async def test_lease_keys_not_persisted(tmp_path):
    wal = tmp_path / "store.wal"
    s1 = await PersistentStore.open(wal)
    lease = await s1.create_lease(ttl=30)
    await s1.put("instances/w1", b"ephemeral", lease_id=lease.id)
    await s1.put("deployments/d", b"durable")
    s1.close_log()

    s2 = await PersistentStore.open(wal)
    assert await s2.get("instances/w1") is None  # owner died with the store
    assert await s2.get("deployments/d") == b"durable"
    s2.close_log()


async def test_put_if_absent_and_lease_conversion_logged(tmp_path):
    wal = tmp_path / "store.wal"
    s1 = await PersistentStore.open(wal)
    assert await s1.put_if_absent("cards/m", b"v1")
    assert not await s1.put_if_absent("cards/m", b"v2")  # no duplicate WAL line
    # converting a durable key to lease-bound scrubs it from the WAL
    lease = await s1.create_lease(ttl=30)
    await s1.put("cards/m", b"v3", lease_id=lease.id)
    s1.close_log()

    s2 = await PersistentStore.open(wal)
    assert await s2.get("cards/m") is None  # lease-governed: not restored
    s2.close_log()


async def test_corrupt_wal_lines_skipped(tmp_path):
    wal = tmp_path / "store.wal"
    s1 = await PersistentStore.open(wal)
    await s1.put("k", b"good")
    s1.close_log()
    with wal.open("a") as fh:
        fh.write("NOT JSON\n")
        fh.write(json.dumps({"op": "put", "key": "k2", "v": "!!!notb64"}) + "\n")
    s2 = await PersistentStore.open(wal)
    assert await s2.get("k") == b"good"
    s2.close_log()


async def test_group_commit_coalesces_fsyncs(tmp_path, monkeypatch):
    """N concurrent writers share fsyncs (group commit): far fewer syncs than
    writes, yet every *acked* write is on disk — a crash immediately after
    the gather (the WAL file as-is, no clean close) replays all of them."""
    calls = {"n": 0}
    real_fsync = os.fsync

    def counting_fsync(fd):
        calls["n"] += 1
        real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    store = await PersistentStore.open(tmp_path / "store.wal")
    n = 32
    await asyncio.gather(*(store.put(f"deployments/{i}", f"v{i}".encode()) for i in range(n)))
    assert store._wal_synced >= store._wal_written == n
    assert calls["n"] < n  # coalesced: one fsync covered a whole batch

    # Simulate a crash: copy the WAL bytes as they are on disk right now
    # (acked => fsynced) and replay from the copy.
    crash_image = tmp_path / "crash.wal"
    crash_image.write_bytes((tmp_path / "store.wal").read_bytes())
    store.close_log()
    replayed = await PersistentStore.open(crash_image)
    for i in range(n):
        assert await replayed.get(f"deployments/{i}") == f"v{i}".encode()
    replayed.close_log()


async def test_group_commit_single_writer_unchanged(tmp_path, monkeypatch):
    """Dormancy: an uncontended writer pays exactly one fsync per mutation —
    identical to the pre-batching behavior."""
    calls = {"n": 0}
    real_fsync = os.fsync

    def counting_fsync(fd):
        calls["n"] += 1
        real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    store = await PersistentStore.open(tmp_path / "store.wal")
    for i in range(5):
        await store.put(f"k{i}", b"v")
    await store.delete("k0")
    assert calls["n"] == 6
    store.close_log()


async def test_store_server_restart_preserves_declarative_state(tmp_path):
    wal = tmp_path / "srv.wal"
    server = await StoreServer(await PersistentStore.open(wal), host="127.0.0.1", port=0).start()
    client = StoreClient.from_url(f"tcp://127.0.0.1:{server.port}")
    await client.put("deployments/x", b"spec")
    await client.close()
    server.store.close_log()
    await server.close()

    server2 = await StoreServer(await PersistentStore.open(wal), host="127.0.0.1", port=0).start()
    client2 = StoreClient.from_url(f"tcp://127.0.0.1:{server2.port}")
    assert await client2.get("deployments/x") == b"spec"
    await client2.close()
    server2.store.close_log()
    await server2.close()
