"""Pallas paged kernels under a sharded mesh (tp over KV heads, dp batch).

Production 8B/70B serving runs the decode/prefill kernels tensor-parallel;
GSPMD cannot partition a pallas_call, so `paged_attention_sharded`
(ops/attention.py) shard_maps the kernel over the mesh — each device runs
on its KV-head slice. These tests run that exact dispatch on the virtual
CPU mesh with the kernels in Pallas interpret mode
(DYNAMO_PALLAS_INTERPRET=1) and pin it against the unsharded reference
formulation. VERDICT r3 item 5 / SURVEY §7 hard parts (a)+(b) combined.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.ops.attention import (
    paged_attention_reference,
    paged_attention_sharded,
    write_kv,
)
from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh

CFG = PRESETS["test-kernel"]  # heads 8, kv 4, head_dim 64: local W=128 at tp=2


@pytest.fixture
def interpret_kernels(monkeypatch):
    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")


def _case(rng, b, t, page_size=8, pages_per_seq=4):
    n_heads, n_kv, hd = CFG.num_heads, CFG.num_kv_heads, CFG.head_dim
    width = n_kv * hd
    num_pages = 1 + b * pages_per_seq
    q = jnp.asarray(rng.standard_normal((b, t, n_heads, hd)), jnp.float32)
    k_cache = jnp.zeros((num_pages, page_size, width), jnp.float32)
    v_cache = jnp.zeros((num_pages, page_size, width), jnp.float32)
    tables = jnp.asarray(
        [[1 + i * pages_per_seq + j for j in range(pages_per_seq)] for i in range(b)],
        jnp.int32,
    )
    # Fill each sequence's cache with ctx_len tokens of K/V, then the query
    # block positions [ctx_len - t, ctx_len).
    ctx = page_size * pages_per_seq - 2
    new_k = jnp.asarray(rng.standard_normal((b, ctx, n_kv, hd)), jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((b, ctx, n_kv, hd)), jnp.float32)
    pos_all = np.arange(ctx)
    slots = np.asarray(
        [[int(tables[i, p // page_size]) * page_size + p % page_size for p in pos_all]
         for i in range(b)], np.int32,
    )
    k_cache, v_cache = write_kv(k_cache, v_cache, new_k, new_v, jnp.asarray(slots))
    positions = jnp.tile(jnp.arange(ctx - t, ctx, dtype=jnp.int32)[None], (b, 1))
    return q, k_cache, v_cache, tables, positions


@pytest.mark.parametrize("t", [1, 8])  # decode kernel / prefill flash kernel
def test_sharded_kernel_matches_reference(interpret_kernels, t):
    from dynamo_tpu.ops import pallas_paged

    rng = np.random.default_rng(0)
    b = 4
    q, k_cache, v_cache, tables, positions = _case(rng, b, t)
    mesh = make_mesh(MeshPlan(dp=2, tp=2), jax.devices()[:4])

    before = dict(pallas_paged.fallback_snapshot())
    got = paged_attention_sharded(
        q, k_cache, v_cache, tables, positions, mesh=mesh, impl="pallas"
    )
    want = paged_attention_reference(
        q, k_cache, v_cache, tables, positions, scale=CFG.head_dim**-0.5
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)
    # The KERNEL ran on the local shard — no new fallback signature.
    assert pallas_paged.fallback_snapshot() == before, "kernel fell back under tp"


def test_sharded_kernel_under_jit_with_dp_sharded_batch(interpret_kernels):
    """The dispatch must compose with the engine's jitted step: dp-sharded
    batch inputs, cache sharded on the W axis, inside jax.jit."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(1)
    b, t = 4, 8
    q, k_cache, v_cache, tables, positions = _case(rng, b, t)
    mesh = make_mesh(MeshPlan(dp=2, tp=2), jax.devices()[:4])
    q = jax.device_put(q, NamedSharding(mesh, P("dp", None, "tp", None)))
    k_cache = jax.device_put(k_cache, NamedSharding(mesh, P(None, None, "tp")))
    v_cache = jax.device_put(v_cache, NamedSharding(mesh, P(None, None, "tp")))
    tables = jax.device_put(tables, NamedSharding(mesh, P("dp", None)))
    positions = jax.device_put(positions, NamedSharding(mesh, P("dp", None)))

    fn = jax.jit(lambda *a: paged_attention_sharded(*a, mesh=mesh, impl="pallas"))
    got = fn(q, k_cache, v_cache, tables, positions)
    want = paged_attention_reference(
        q, k_cache, v_cache, tables, positions, scale=CFG.head_dim**-0.5
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)


def test_full_forward_pallas_under_mesh(interpret_kernels):
    """llama.forward with attn_impl="pallas" and a tp>1 mesh routes through
    the sharded kernel dispatch and matches the reference forward."""
    mesh = make_mesh(MeshPlan(dp=2, tp=2), jax.devices()[:4])
    params = llama.init_params(CFG, 0)
    page_size, num_pages = 8, 16
    b, t = 2, 8
    tokens = jnp.asarray(np.arange(b * t).reshape(b, t) % CFG.vocab_size, jnp.int32)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None], (b, 1))
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    slots = jnp.take_along_axis(tables, positions // page_size, axis=1) * page_size + positions % page_size
    last = jnp.full((b,), t - 1, jnp.int32)

    def run(impl, use_mesh):
        kc, vc = llama.init_kv_cache(CFG, num_pages, page_size)
        logits, _, _ = llama.forward(
            params, CFG, tokens, positions, kc, vc, tables, slots, last,
            attn_impl=impl, mesh=mesh if use_mesh else None,
        )
        return np.asarray(logits)

    want = run("reference", False)
    got = run("pallas", True)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
