"""MLA decode Pallas kernel (ops/pallas_mla.py) vs the gather formulation.

The kernel is the single-chip decode hot path for DeepSeek-family MLA
models; the gather formulation (models/mla.py) is its bit-level reference.
Runs in Pallas interpret mode on CPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.pallas_mla import mla_decode_supported, mla_paged_decode

# Kernel-geometry MLA config: r_kv lane-aligned (128), dr 64 — the V3 shape
# ratios at test scale.
CFG = ModelConfig(
    name="test-mla-kernel", vocab_size=256, hidden_size=128, num_layers=2,
    num_heads=4, num_kv_heads=4, head_dim=32, intermediate_size=128,
    rope_theta=10000.0, max_position=512, tie_embeddings=True, dtype="float32",
    attn_type="mla", q_lora_rank=0, kv_lora_rank=128,
    qk_nope_head_dim=32, qk_rope_head_dim=64, v_head_dim=32,
)


def test_supported_predicate():
    assert mla_decode_supported(128, 128)
    assert mla_decode_supported(512, 128)
    assert not mla_decode_supported(96, 128)  # latent off the lane grid
    assert not mla_decode_supported(512, 64)  # unpadded rope stream


def test_mla_kernel_matches_gather_formulation():
    rng = np.random.default_rng(0)
    b, page_size, pages_per_seq = 4, 8, 3
    r_kv, dr = CFG.kv_lora_rank, CFG.qk_rope_head_dim
    n_heads = CFG.num_heads
    num_pages = 1 + b * pages_per_seq

    c_cache = jnp.asarray(rng.standard_normal((num_pages, page_size, r_kv)), jnp.float32)
    r_cache = jnp.asarray(rng.standard_normal((num_pages, page_size, dr)), jnp.float32)
    tables = jnp.asarray(
        [[1 + i * pages_per_seq + j for j in range(pages_per_seq)] for i in range(b)],
        jnp.int32,
    )
    # Ragged real lengths per sequence (tail block exercise).
    lengths = [5, 8, 17, 24]
    positions = jnp.asarray([[n - 1] for n in lengths], jnp.int32)
    q_lat = jnp.asarray(rng.standard_normal((b, n_heads, r_kv)), jnp.float32)
    q_rope = jnp.asarray(rng.standard_normal((b, n_heads, dr)), jnp.float32)
    scale = (CFG.qk_nope_head_dim + dr) ** -0.5

    got = mla_paged_decode(
        q_lat, q_rope, c_cache, r_cache, tables, positions,
        scale=scale, interpret=True,
    )

    # Gather-formulation reference (same math as models/mla.py).
    s = pages_per_seq * page_size
    c_pages = c_cache[tables.reshape(-1)].reshape(b, s, r_kv)
    r_pages = r_cache[tables.reshape(-1)].reshape(b, s, dr)
    logits = (
        jnp.einsum("bhr,bsr->bhs", q_lat, c_pages)
        + jnp.einsum("bhr,bsr->bhs", q_rope, r_pages)
    ) * scale
    key_pos = jnp.arange(s)[None, None, :]
    logits = jnp.where(key_pos <= positions[:, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    want = jnp.einsum("bhs,bsr->bhr", probs, c_pages)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)


def test_full_mla_forward_kernel_vs_gather(monkeypatch):
    """End-to-end decode step through llama.forward: attn_impl="pallas"
    (kernel, interpret) must match attn_impl="reference" (gather)."""
    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")
    params = llama.init_params(CFG, 0)
    page_size, num_pages = 8, 16
    b = 2
    k_cache, v_cache = llama.init_kv_cache(CFG, num_pages, page_size)
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)

    # Prefill 8 tokens (gather path: T>1), then one decode step each way.
    t = 8
    tokens = jnp.asarray(np.arange(b * t).reshape(b, t) % CFG.vocab_size, jnp.int32)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None], (b, 1))
    slots = jnp.take_along_axis(tables, positions // page_size, axis=1) * page_size + positions % page_size
    last = jnp.full((b,), t - 1, jnp.int32)
    _, k_cache, v_cache = llama.forward(
        params, CFG, tokens, positions, k_cache, v_cache, tables, slots, last,
        attn_impl="reference",
    )

    def decode(impl):
        tok = jnp.asarray([[7], [9]], jnp.int32)
        pos = jnp.asarray([[t], [t]], jnp.int32)
        slot = jnp.take_along_axis(tables, pos // page_size, axis=1) * page_size + pos % page_size
        logits, _, _ = llama.forward(
            params, CFG, tok, pos, k_cache, v_cache, tables, slot,
            jnp.zeros((b,), jnp.int32), attn_impl=impl,
        )
        return np.asarray(logits)

    np.testing.assert_allclose(
        decode("pallas"), decode("reference"), rtol=2e-2, atol=2e-2
    )


def test_mla_kernel_under_tp_mesh(monkeypatch):
    """MLA decode kernel via shard_map on a (dp x tp) mesh: query heads
    shard, the latent cache replicates (MQA), output matches the
    single-device gather formulation."""
    from dynamo_tpu.ops.pallas_mla import mla_paged_decode_sharded
    from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh

    rng = np.random.default_rng(2)
    b, page_size, pages_per_seq = 4, 8, 3
    r_kv, dr = CFG.kv_lora_rank, CFG.qk_rope_head_dim
    n_heads = CFG.num_heads  # 4: splits over tp=2
    num_pages = 1 + b * pages_per_seq
    c_cache = jnp.asarray(rng.standard_normal((num_pages, page_size, r_kv)), jnp.float32)
    r_cache = jnp.asarray(rng.standard_normal((num_pages, page_size, dr)), jnp.float32)
    tables = jnp.asarray(
        [[1 + i * pages_per_seq + j for j in range(pages_per_seq)] for i in range(b)],
        jnp.int32,
    )
    positions = jnp.asarray([[5], [11], [17], [23]], jnp.int32)
    q_lat = jnp.asarray(rng.standard_normal((b, n_heads, r_kv)), jnp.float32)
    q_rope = jnp.asarray(rng.standard_normal((b, n_heads, dr)), jnp.float32)
    scale = (CFG.qk_nope_head_dim + dr) ** -0.5

    mesh = make_mesh(MeshPlan(dp=2, tp=2), jax.devices()[:4])
    got = mla_paged_decode_sharded(
        q_lat, q_rope, c_cache, r_cache, tables, positions,
        mesh=mesh, scale=scale, interpret=True,
    )

    s = pages_per_seq * page_size
    c_pages = c_cache[tables.reshape(-1)].reshape(b, s, r_kv)
    r_pages = r_cache[tables.reshape(-1)].reshape(b, s, dr)
    logits = (
        jnp.einsum("bhr,bsr->bhs", q_lat, c_pages)
        + jnp.einsum("bhr,bsr->bhs", q_rope, r_pages)
    ) * scale
    key_pos = jnp.arange(s)[None, None, :]
    logits = jnp.where(key_pos <= positions[:, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    want = jnp.einsum("bhs,bsr->bhr", probs, c_pages)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)
