"""Checkpoint rope-layout conventions (ADVICE r2 high/medium findings).

Two ecosystems store Q/K rope dims in *interleaved pair* order while this
framework (like HF Llama) runs *half-split* rope everywhere:

- llama.cpp-converted GGUFs: the converter permutes whole Q/K heads of
  llama-family (arch "llama") models into GGML NORM order.
- DeepSeek-V2/V3 HF checkpoints (``rope_interleave=True``): q/kv_a rope
  segments are interleaved; HF modeling un-interleaves the *activations*
  (`modeling_deepseek_v3.py:apply_rotary_pos_emb_interleave`).

The loaders must invert these at load time (and writers re-apply on save).
These tests pin the permutations against independent re-implementations of
the source conventions — not against the loader's own inverse.
"""

import dataclasses

import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.models.loader import rope_load_perm, rope_save_perm


def _llamacpp_permute(w: np.ndarray, n_head: int) -> np.ndarray:
    """llama.cpp convert_hf_to_gguf LlamaModel.permute, re-implemented from
    its documented semantics: HF half-split rows -> GGML interleaved rows."""
    return (
        w.reshape(n_head, 2, w.shape[0] // n_head // 2, *w.shape[1:])
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


def _hf_interleave(w: np.ndarray, n_head: int, head_size: int, rope_dim: int) -> np.ndarray:
    """DeepSeek HF convention: produce the *checkpoint* (interleaved) row
    order from half-split rows — per head, rope row ``2d+p`` holds
    half-split row ``p*half+d``; non-rope rows untouched."""
    out = w.copy()
    half = rope_dim // 2
    for h in range(n_head):
        off = h * head_size + (head_size - rope_dim)
        seg = w[off : off + rope_dim].copy()
        for d in range(half):
            for p in range(2):
                out[off + 2 * d + p] = seg[p * half + d]
    return out


def test_rope_load_perm_inverts_llamacpp_permute():
    rng = np.random.default_rng(0)
    n_head, head_dim = 4, 16
    hf = rng.standard_normal((n_head * head_dim, 8))
    gguf = _llamacpp_permute(hf, n_head)
    perm = rope_load_perm(n_head, head_dim, head_dim)
    np.testing.assert_array_equal(gguf[perm], hf)


def test_rope_save_perm_is_inverse():
    perm = rope_load_perm(3, 24, 8)
    inv = rope_save_perm(3, 24, 8)
    n = 3 * 24
    np.testing.assert_array_equal(perm[inv], np.arange(n))
    np.testing.assert_array_equal(inv[perm], np.arange(n))


def test_rope_load_perm_inverts_hf_interleave_partial_head():
    """MLA heads rope only their trailing qk_rope_head_dim rows."""
    rng = np.random.default_rng(1)
    n_head, head_size, rope_dim = 2, 24, 8
    half_split = rng.standard_normal((n_head * head_size, 6))
    ckpt = _hf_interleave(half_split, n_head, head_size, rope_dim)
    perm = rope_load_perm(n_head, head_size, rope_dim)
    np.testing.assert_array_equal(ckpt[perm], half_split)


def test_gguf_llamacpp_converted_checkpoint_loads_correctly(tmp_path):
    """Simulate a llama.cpp conversion of an HF checkpoint (independent
    permute implementation) and assert the GGUF loader recovers the original
    HF-convention weights — the ADVICE r2 'high' finding."""
    from dynamo_tpu.models.gguf import load_gguf_params, write_gguf

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    n_h, n_kv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    tensors: dict[str, np.ndarray] = {
        "token_embd.weight": np.asarray(params["embed"], np.float32),
        "output_norm.weight": np.asarray(params["norm_f"], np.float32),
    }
    lp = params["layers"]
    for li in range(cfg.num_layers):
        for leaf, suffix in [
            ("attn_norm", "attn_norm.weight"), ("mlp_norm", "ffn_norm.weight"),
        ]:
            tensors[f"blk.{li}.{suffix}"] = np.asarray(lp[leaf][li], np.float32)
        for leaf, suffix, permute_heads in [
            ("wq", "attn_q.weight", n_h), ("wk", "attn_k.weight", n_kv),
            ("wv", "attn_v.weight", None), ("wo", "attn_output.weight", None),
            ("w_gate", "ffn_gate.weight", None), ("w_up", "ffn_up.weight", None),
            ("w_down", "ffn_down.weight", None),
        ]:
            torch_w = np.asarray(lp[leaf][li], np.float32).T  # [out, in]
            if permute_heads is not None:
                torch_w = _llamacpp_permute(torch_w, permute_heads)
            tensors[f"blk.{li}.{suffix}"] = np.ascontiguousarray(torch_w)

    md = {"general.architecture": "llama", "llama.block_count": cfg.num_layers}
    path = tmp_path / "converted.gguf"
    write_gguf(path, md, tensors)

    loaded = load_gguf_params(path, cfg, dtype="float32")
    for leaf in ("wq", "wk", "wv", "wo"):
        np.testing.assert_allclose(
            np.asarray(loaded["layers"][leaf]), np.asarray(lp[leaf]), rtol=1e-6, atol=1e-6,
            err_msg=leaf,
        )


def test_gguf_writer_loader_round_trip_with_permutation(tmp_path):
    """Our writer exports under arch 'llama' (now permuting to GGML order);
    the loader must invert it exactly."""
    from dynamo_tpu.models.gguf import load_gguf_params, save_params_gguf

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 3)
    path = tmp_path / "export.gguf"
    save_params_gguf(path, cfg, params)
    loaded = load_gguf_params(path, cfg, dtype="float32")
    for leaf in ("wq", "wk"):
        np.testing.assert_allclose(
            np.asarray(loaded["layers"][leaf]), np.asarray(params["layers"][leaf]),
            rtol=1e-3, atol=1e-3, err_msg=leaf,
        )


def test_mla_interleaved_checkpoint_loads_correctly(tmp_path):
    """Simulate a DeepSeek HF checkpoint (rope_interleave=True): write the
    safetensors with *interleaved* rope rows via the independent formula and
    assert load_params recovers half-split weights — the ADVICE r2 'medium'
    finding."""
    from safetensors.numpy import save_file

    from dynamo_tpu.models.loader import load_params, save_params

    cfg = dataclasses.replace(PRESETS["test-tiny-mla"], rope_interleave=True)
    params = llama.init_params(cfg, 5)

    # First materialize the HF layout via save_params (which applies the
    # inverse perm), then independently verify the written rope rows match
    # the hand-rolled interleave of the in-memory half-split weights.
    save_params(tmp_path, cfg, params)
    loaded = load_params(tmp_path, cfg, dtype="float32")
    for leaf in ("w_q_b", "w_kv_a", "w_uk", "w_uv", "wo_mla"):
        np.testing.assert_allclose(
            np.asarray(loaded["layers"][leaf]), np.asarray(params["layers"][leaf]),
            rtol=1e-6, atol=1e-6, err_msg=leaf,
        )

    # Absolute check against the independent interleave implementation.
    from safetensors import safe_open

    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    with safe_open(str(tmp_path / "model.safetensors"), framework="numpy") as f:
        written = f.get_tensor("model.layers.0.self_attn.q_b_proj.weight")
    half_split_torch = np.asarray(params["layers"]["w_q_b"][0], np.float32).T
    expect = _hf_interleave(half_split_torch, cfg.num_heads, dn + dr, dr)
    np.testing.assert_allclose(written, expect, rtol=1e-6, atol=1e-6)


def test_mla_forward_differs_if_permutation_skipped(tmp_path):
    """Guard that the permutation is load-bearing: loading an interleaved
    checkpoint as if half-split must change the forward pass (otherwise the
    fix is vacuous for this geometry)."""
    import jax.numpy as jnp

    from dynamo_tpu.models.loader import load_params, save_params

    cfg = dataclasses.replace(PRESETS["test-tiny-mla"], rope_interleave=True)
    params = llama.init_params(cfg, 7)
    save_params(tmp_path, cfg, params)
    cfg_no_fix = dataclasses.replace(cfg, rope_interleave=False)
    wrong = load_params(tmp_path, cfg_no_fix, dtype="float32")

    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    tables = jnp.asarray([[1]], jnp.int32)
    slots = jnp.asarray([[8, 9, 10, 11]], jnp.int32)  # page 1 @ page_size 8
    last = jnp.asarray([3], jnp.int32)

    def run(p):
        k, v = llama.init_kv_cache(cfg, num_pages=2, page_size=8)
        logits, _, _ = llama.forward(p, cfg, tokens, positions, k, v, tables, slots, last)
        return np.asarray(logits)

    good, bad = run(params), run(wrong)
    assert not np.allclose(good, bad, atol=1e-4), (
        "permuted and unpermuted loads agree — the rope permutation is not being exercised"
    )
