"""Tracing (device trace + spans) and the object store.

Device traces run against the CPU backend here (same jax.profiler API the
TPU path uses); spans assert on structured log records; object store tests
cover chunking, checksums, partial uploads, and card artifact round-trips.
"""

import json
import logging

import pytest

from dynamo_tpu.runtime.discovery import MemoryStore
from dynamo_tpu.runtime.objects import ObjectError, ObjectStore, is_object_url, object_name


async def test_object_roundtrip_chunked():
    objects = ObjectStore(MemoryStore(), chunk_size=8)
    data = bytes(range(256)) * 3  # 768 bytes -> 96 chunks
    url = await objects.put("art/blob.bin", data)
    assert url == "object://art/blob.bin"
    assert await objects.get("art/blob.bin") == data
    meta = await objects.stat("art/blob.bin")
    assert meta["chunks"] == 96 and meta["size"] == 768
    assert await objects.delete("art/blob.bin")
    with pytest.raises(ObjectError, match="not found"):
        await objects.get("art/blob.bin")


async def test_overwrite_cleans_orphan_chunks():
    store = MemoryStore()
    objects = ObjectStore(store, chunk_size=4)
    await objects.put("x", b"0123456789ab")  # 3 chunks
    await objects.put("x", b"zz")  # 1 chunk
    assert await objects.get("x") == b"zz"
    assert await store.get("objects/x/chunk/00000001") is None
    assert await store.get("objects/x/chunk/00000002") is None


async def test_card_dir_tokenizer_uploaded(tmp_path):
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.sentencepiece import NORMAL, UNKNOWN, write_model

    mdir = tmp_path / "model"
    mdir.mkdir()
    (mdir / "tokenizer.model").write_bytes(
        write_model([("<unk>", 0.0, UNKNOWN), ("▁a", -1.0, NORMAL)], bos_id=-1, eos_id=-1)
    )
    objects = ObjectStore(MemoryStore())
    card = ModelDeploymentCard(name="m2", tokenizer=str(mdir))
    await card.move_to_store(objects)
    assert card.tokenizer == "object://cards/m2/tokenizer.model"


async def test_object_missing_chunk_detected():
    store = MemoryStore()
    objects = ObjectStore(store, chunk_size=4)
    await objects.put("x", b"0123456789")
    await store.delete("objects/x/chunk/00000001")
    with pytest.raises(ObjectError, match="missing chunk"):
        await objects.get("x")


async def test_object_checksum_detects_corruption():
    store = MemoryStore()
    objects = ObjectStore(store, chunk_size=4)
    await objects.put("x", b"0123456789")
    await store.put("objects/x/chunk/00000000", b"9999")
    with pytest.raises(ObjectError, match="checksum"):
        await objects.get("x")


def test_object_url_helpers():
    assert is_object_url("object://a/b")
    assert not is_object_url("/tmp/a")
    assert not is_object_url(None)
    assert object_name("object://a/b") == "a/b"
    with pytest.raises(ObjectError):
        object_name("/tmp/nope")


async def test_card_artifact_distribution(tmp_path):
    """Card -> object store -> fresh 'worker host' -> identical tokenizer."""
    from dynamo_tpu.model_card import ModelDeploymentCard
    from dynamo_tpu.sentencepiece import NORMAL, UNKNOWN, write_model
    from dynamo_tpu.tokenizer import load_tokenizer

    pieces = [("<unk>", 0.0, UNKNOWN), ("▁hi", -1.0, NORMAL), ("▁yo", -1.2, NORMAL)]
    src = tmp_path / "src" / "tokenizer.model"
    src.parent.mkdir()
    src.write_bytes(write_model(pieces, bos_id=-1, eos_id=-1))

    objects = ObjectStore(MemoryStore())
    card = ModelDeploymentCard(name="m1", tokenizer=str(src))
    await card.move_to_store(objects)
    assert is_object_url(card.tokenizer)

    # simulate shipping the card: serialize/deserialize, resolve elsewhere
    card2 = ModelDeploymentCard.from_bytes(card.to_bytes())
    cache = tmp_path / "worker-cache"
    await card2.resolve_from_store(objects, cache)
    assert not is_object_url(card2.tokenizer)
    tok = load_tokenizer(card2.tokenizer)
    assert tok.encode("hi yo") == [1, 2]


async def test_device_trace_writes_xplane(tmp_path):
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.tracing import device_trace, trace_running

    with device_trace(str(tmp_path / "trace")):
        assert trace_running()
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    assert not trace_running()
    dumps = list((tmp_path / "trace").rglob("*.xplane.pb"))
    assert dumps, "no xplane dump written"


async def test_profile_http_endpoint(tmp_path):
    import aiohttp

    from dynamo_tpu.launch import run_local

    handles = await run_local("test-tiny", port=0, mock=True, num_pages=64)
    try:
        port = handles["port"]
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{port}/engine/profile",
                json={"seconds": 0.2, "dir": str(tmp_path / "t")},
            )
            body = await r.json()
            assert r.status == 200
            assert body["trace_dir"]
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for s in handles["services"]:
            await s.close()
        await handles["runtime"].close()


def test_span_logs_structured_fields(caplog):
    from dynamo_tpu.tracing import Span

    with caplog.at_level(logging.DEBUG, logger="dynamo.trace"):
        with Span("prefill", request_id="r1", tokens=7):
            pass
        with pytest.raises(ValueError):
            with Span("decode", request_id="r2"):
                raise ValueError("boom")
    records = [r for r in caplog.records if getattr(r, "span", None)]
    assert records[0].span == "prefill" and records[0].request_id == "r1"
    assert records[0].duration_ms >= 0
    assert records[1].span == "decode" and records[1].error == "ValueError"


def test_jsonl_formatter_flattens_span_fields():
    from dynamo_tpu.runtime.logging import JsonlFormatter
    from dynamo_tpu.tracing import Span

    captured = []

    class Sink(logging.Handler):
        def emit(self, record):
            captured.append(JsonlFormatter().format(record))

    log = logging.getLogger("dynamo.trace")
    sink = Sink(level=logging.DEBUG)
    log.addHandler(sink)
    old = log.level
    log.setLevel(logging.DEBUG)
    try:
        with Span("step", request_id="r9", tokens=3):
            pass
    finally:
        log.setLevel(old)
        log.removeHandler(sink)
    doc = json.loads(captured[-1])
    assert doc["span"] == "step" and doc["request_id"] == "r9" and doc["tokens"] == 3
