"""Weight-only quantized serving — int8 (per-channel) and packed int4
(group-wise): quantization error bounds, forward closeness, sharding of
quantized leaves, engine integration.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.models.quant import (
    init_params_quantized,
    is_quantized,
    maybe_dequant,
    pack_int4,
    quantize_leaf,
    quantize_leaf_int4,
    quantize_params,
    unpack_int4,
)


def test_quantize_leaf_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    q = quantize_leaf(w)
    assert q["qw"].dtype == jnp.int8 and q["scale"].shape == (128,)
    back = np.asarray(maybe_dequant(q, jnp.float32))
    # per-channel: error <= half a step of that channel's scale
    step = np.abs(np.asarray(w)).max(axis=0) / 127.0
    err = np.abs(back - np.asarray(w))
    assert (err <= step[None, :] * 0.51 + 1e-7).all()


def test_quantize_params_selects_matmul_leaves():
    cfg = dataclasses.replace(PRESETS["test-tiny"], tie_embeddings=False)
    params = quantize_params(llama.init_params(cfg, 1))
    assert is_quantized(params["layers"]["wq"])
    assert is_quantized(params["layers"]["w_down"])
    assert is_quantized(params["lm_head"])
    # non-matmul leaves untouched
    assert not is_quantized(params["embed"]) and params["embed"].dtype != jnp.int8
    assert params["layers"]["attn_norm"].dtype != jnp.int8
    # idempotent
    again = quantize_params(params)
    assert again["layers"]["wq"] is params["layers"]["wq"]


def test_moe_params_quantize():
    cfg = PRESETS["test-tiny-moe"]
    params = quantize_params(llama.init_params(cfg, 2))
    lq = params["layers"]
    assert is_quantized(lq["w_gate"]) and lq["w_gate"]["qw"].ndim == 4
    assert lq["w_gate"]["scale"].ndim == 3  # [L, E, F]
    assert not is_quantized(lq["router"])  # routing stays full precision


def _tiny_forward(cfg, params):
    B, T, PAGES, PS = 2, 8, 8, 16
    tokens = jnp.arange(B * T, dtype=jnp.int32).reshape(B, T) % cfg.vocab_size
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
    kc, vc = llama.init_kv_cache(cfg, PAGES, PS)
    tables = jnp.arange(B * 4, dtype=jnp.int32).reshape(B, 4)
    slots = (tables[:, :1] * PS + jnp.arange(T)[None]).astype(jnp.int32)
    last = jnp.full((B,), T - 1, jnp.int32)
    logits, _, _ = llama.forward(
        params, cfg, tokens, positions, kc, vc, tables, slots, last, attn_impl="reference"
    )
    return np.asarray(logits, np.float32)


def test_forward_close_to_unquantized():
    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 3)
    a = _tiny_forward(cfg, params)
    b = _tiny_forward(cfg, quantize_params(params))
    # same argmax decisions and close logits (int8 weight error is <1%)
    assert (a.argmax(-1) == b.argmax(-1)).mean() > 0.95
    np.testing.assert_allclose(a, b, atol=0.25, rtol=0.1)


def test_quantized_sharding_specs():
    from jax.sharding import Mesh, PartitionSpec as P

    from dynamo_tpu.parallel.sharding import param_shardings

    cfg = dataclasses.replace(PRESETS["test-tiny-moe"], tie_embeddings=False)
    params = quantize_params(llama.init_params(cfg, 4))
    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("ep", "tp"))
    sh = param_shardings(mesh, params)
    assert sh["layers"]["wq"]["qw"].spec == P(None, None, "tp")
    assert sh["layers"]["wq"]["scale"].spec == P(None, "tp")
    assert sh["layers"]["w_gate"]["qw"].spec == P(None, "ep", None, "tp")
    assert sh["layers"]["w_gate"]["scale"].spec == P(None, "ep", "tp")
    assert sh["lm_head"]["qw"].spec == P(None, "tp")
    assert sh["lm_head"]["scale"].spec == P("tp")


# ---------------------------------------------------------------------------
# Packed int4: nibble layout, group-wise scales, parity, init, sharding
# ---------------------------------------------------------------------------


def test_int4_pack_unpack_roundtrip():
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.integers(-8, 8, size=(3, 10, 7)), jnp.int8)
    packed = pack_int4(q)
    assert packed.shape == (3, 5, 7) and packed.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed)), np.asarray(q))


def test_quantize_leaf_int4_error_bound():
    rng = np.random.default_rng(6)
    w = jnp.asarray(rng.standard_normal((96, 40)), jnp.float32)
    q = quantize_leaf_int4(w, group_size=32)
    assert q["qw4"].shape == (48, 40) and q["qw4"].dtype == jnp.int8
    assert q["scale"].shape == (3, 40)  # one scale per 32-row group per column
    back = np.asarray(maybe_dequant(q, jnp.float32))
    wg = np.asarray(w).reshape(3, 32, 40)
    step = np.abs(wg).max(axis=1) / 7.0  # [G, d_out]
    err = np.abs(back.reshape(3, 32, 40) - wg)
    assert (err <= step[:, None, :] * 0.51 + 1e-6).all()


def test_int4_group_size_shrinks_to_divisor():
    # d_in=24 with requested group 128: largest even divisor <= 24 is 24.
    w = jnp.asarray(np.random.default_rng(7).standard_normal((24, 8)), jnp.float32)
    q = quantize_leaf_int4(w, group_size=128)
    assert q["scale"].shape == (1, 8)
    # d_in=48, requested 32 (doesn't divide): shrink to 24 -> 2 groups.
    w = jnp.asarray(np.random.default_rng(8).standard_normal((48, 8)), jnp.float32)
    q = quantize_leaf_int4(w, group_size=32)
    assert 48 % q["scale"].shape[0] == 0 and q["scale"].shape[0] > 1


def test_quantize_params_int4_selects_matmul_leaves():
    cfg = dataclasses.replace(PRESETS["test-tiny"], tie_embeddings=False)
    params = quantize_params(llama.init_params(cfg, 7), mode="int4")
    wq = params["layers"]["wq"]
    assert is_quantized(wq) and "qw4" in wq
    assert wq["qw4"].shape[-2] * 2 == cfg.hidden_size  # packed bytes: d_in/2
    assert is_quantized(params["lm_head"]) and "qw4" in params["lm_head"]
    assert not is_quantized(params["embed"])
    # dequant restores the full-width shape
    back = maybe_dequant(wq)
    assert back.shape[-2] == cfg.hidden_size


def test_forward_close_int4():
    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 8)
    a = _tiny_forward(cfg, params)
    b = _tiny_forward(cfg, quantize_params(params, mode="int4"))
    # int4 group-wise is coarser than int8 — on a 2-layer RANDOM model the
    # ~7% weight error compounds into O(1) logit deltas, so exact-argmax and
    # tight allclose are flaky. The distribution must still track: greedy
    # pick within the full-precision top-5, high logit correlation, bounded
    # mean error. (Golden-parity on trained weights lives in the GGUF tests.)
    top5 = np.argsort(a, -1)[:, -5:]
    for i, t in enumerate(b.argmax(-1)):
        assert t in top5[i]
        x, y = a[i] - a[i].mean(), b[i] - b[i].mean()
        corr = (x * y).sum() / np.sqrt((x * x).sum() * (y * y).sum())
        assert corr > 0.85
        assert np.abs(a[i] - b[i]).mean() < 0.5


def test_unknown_quant_mode_fails_loudly():
    cfg = PRESETS["test-tiny"]
    with pytest.raises(ValueError, match="unknown quantization mode"):
        quantize_params(llama.init_params(cfg, 9), mode="int3")
    with pytest.raises(ValueError, match="unknown quantization mode"):
        init_params_quantized(cfg, 0, mode="fp4")


def test_init_params_quantized_matches_quantize_after_init():
    """Shapes/dtypes of the direct-init tree must match quantize-after-init
    exactly (both modes), and the leaves must be finite under dequant —
    the whole point is benchmarking without the full-precision peak."""
    cfg = PRESETS["test-tiny"]
    for mode in ("int8", "int4"):
        direct = init_params_quantized(cfg, 0, mode=mode)
        ref = quantize_params(llama.init_params(cfg, 0), mode=mode)
        sa = jax.tree.map(lambda a: (tuple(a.shape), str(a.dtype)), direct)
        sb = jax.tree.map(lambda a: (tuple(a.shape), str(a.dtype)), ref)
        assert sa == sb, mode
        back = np.asarray(maybe_dequant(direct["layers"]["wq"], jnp.float32))
        assert np.isfinite(back).all()


def test_int4_sharding_specs():
    from jax.sharding import Mesh, PartitionSpec as P

    from dynamo_tpu.parallel.sharding import param_shardings

    cfg = dataclasses.replace(PRESETS["test-tiny-moe"], tie_embeddings=False)
    params = quantize_params(llama.init_params(cfg, 10), mode="int4")
    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("ep", "tp"))
    sh = param_shardings(mesh, params)
    # qw4 keeps the float weight's rank -> same spec; group scales subdivide
    # d_in exactly like the packed byte axis, so they inherit the spec too.
    assert sh["layers"]["wq"]["qw4"].spec == P(None, None, "tp")
    assert sh["layers"]["wq"]["scale"].spec == P(None, None, "tp")
    assert sh["layers"]["w_gate"]["qw4"].spec == P(None, "ep", None, "tp")
    assert sh["layers"]["w_gate"]["scale"].spec == P(None, "ep", None, "tp")
    assert sh["lm_head"]["qw4"].spec == P(None, "tp")
    assert sh["lm_head"]["scale"].spec == P(None, "tp")


def test_int4_fusion_audit_report():
    """The HLO fusion audit runs and reports coherently on this backend.

    The fusion *verdict* is a TPU-pipeline property (CPU dot kernels take
    materialized operands, so ``ok`` is expected False here); what tier-1
    pins is that the audit executes, the checks agree with the evidence
    they cite, and strictness gates on backend/override as documented.
    """
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from check_int4_fusion import audit_int4_fusion

    report = audit_int4_fusion(batch=2, d_in=256, d_out=256, group_size=64)
    assert report["shape"]["d_in"] == 256
    assert report["full_weight_bytes"] == 256 * 256 * 2
    assert report["ok"] == (report["temp_ok"] and report["hlo_ok"])
    # hlo_ok and the offender list must tell the same story.
    assert report["hlo_ok"] == (not report["entry_offenders"])
    if jax.default_backend() == "tpu":
        assert report["strict"] and report["ok"], report["entry_offenders"]
    else:
        assert not report["strict"]  # advisory off-chip unless forced
    forced = os.environ.get("DYN_INT4_FUSION_STRICT")
    try:
        os.environ["DYN_INT4_FUSION_STRICT"] = "1"
        assert audit_int4_fusion(batch=2, d_in=256, d_out=256, group_size=64)[
            "strict"
        ]
    finally:
        if forced is None:
            os.environ.pop("DYN_INT4_FUSION_STRICT", None)
        else:
            os.environ["DYN_INT4_FUSION_STRICT"] = forced


@pytest.mark.parametrize("mode", ["int8", "int4"])
async def test_quantized_serving_end_to_end(mode):
    import aiohttp

    from dynamo_tpu.launch import run_local

    handles = await run_local(
        "test-tiny", port=0, num_pages=64, max_batch_size=4, quantize=mode
    )
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{handles['port']}/v1/completions",
                json={"model": "test-tiny", "prompt": "ab", "max_tokens": 4},
            )
            doc = await r.json()
            assert r.status == 200
            assert doc["usage"]["completion_tokens"] == 4
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
