"""Weight-only int8 serving: quantization error bounds, forward closeness,
sharding of quantized leaves, engine integration.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.models.quant import is_quantized, maybe_dequant, quantize_leaf, quantize_params


def test_quantize_leaf_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    q = quantize_leaf(w)
    assert q["qw"].dtype == jnp.int8 and q["scale"].shape == (128,)
    back = np.asarray(maybe_dequant(q, jnp.float32))
    # per-channel: error <= half a step of that channel's scale
    step = np.abs(np.asarray(w)).max(axis=0) / 127.0
    err = np.abs(back - np.asarray(w))
    assert (err <= step[None, :] * 0.51 + 1e-7).all()


def test_quantize_params_selects_matmul_leaves():
    cfg = dataclasses.replace(PRESETS["test-tiny"], tie_embeddings=False)
    params = quantize_params(llama.init_params(cfg, 1))
    assert is_quantized(params["layers"]["wq"])
    assert is_quantized(params["layers"]["w_down"])
    assert is_quantized(params["lm_head"])
    # non-matmul leaves untouched
    assert not is_quantized(params["embed"]) and params["embed"].dtype != jnp.int8
    assert params["layers"]["attn_norm"].dtype != jnp.int8
    # idempotent
    again = quantize_params(params)
    assert again["layers"]["wq"] is params["layers"]["wq"]


def test_moe_params_quantize():
    cfg = PRESETS["test-tiny-moe"]
    params = quantize_params(llama.init_params(cfg, 2))
    lq = params["layers"]
    assert is_quantized(lq["w_gate"]) and lq["w_gate"]["qw"].ndim == 4
    assert lq["w_gate"]["scale"].ndim == 3  # [L, E, F]
    assert not is_quantized(lq["router"])  # routing stays full precision


def test_forward_close_to_unquantized():
    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 3)
    qparams = quantize_params(params)
    B, T, PAGES, PS = 2, 8, 8, 16
    tokens = jnp.arange(B * T, dtype=jnp.int32).reshape(B, T) % cfg.vocab_size
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
    kc, vc = llama.init_kv_cache(cfg, PAGES, PS)
    tables = jnp.arange(B * 4, dtype=jnp.int32).reshape(B, 4)
    slots = (tables[:, :1] * PS + jnp.arange(T)[None]).astype(jnp.int32)
    last = jnp.full((B,), T - 1, jnp.int32)

    def fwd(p):
        logits, _, _ = llama.forward(
            p, cfg, tokens, positions, kc, vc, tables, slots, last, attn_impl="reference"
        )
        return np.asarray(logits, np.float32)

    a, b = fwd(params), fwd(qparams)
    # same argmax decisions and close logits (int8 weight error is <1%)
    assert (a.argmax(-1) == b.argmax(-1)).mean() > 0.95
    np.testing.assert_allclose(a, b, atol=0.25, rtol=0.1)


def test_quantized_sharding_specs():
    from jax.sharding import Mesh, PartitionSpec as P

    from dynamo_tpu.parallel.sharding import param_shardings

    cfg = dataclasses.replace(PRESETS["test-tiny-moe"], tie_embeddings=False)
    params = quantize_params(llama.init_params(cfg, 4))
    devices = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devices, ("ep", "tp"))
    sh = param_shardings(mesh, params)
    assert sh["layers"]["wq"]["qw"].spec == P(None, None, "tp")
    assert sh["layers"]["wq"]["scale"].spec == P(None, "tp")
    assert sh["layers"]["w_gate"]["qw"].spec == P(None, "ep", None, "tp")
    assert sh["layers"]["w_gate"]["scale"].spec == P(None, "ep", "tp")
    assert sh["lm_head"]["qw"].spec == P(None, "tp")
    assert sh["lm_head"]["scale"].spec == P("tp")


async def test_quantized_serving_end_to_end():
    import aiohttp

    from dynamo_tpu.launch import run_local

    handles = await run_local(
        "test-tiny", port=0, num_pages=64, max_batch_size=4, quantize="int8"
    )
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{handles['port']}/v1/completions",
                json={"model": "test-tiny", "prompt": "ab", "max_tokens": 4},
            )
            doc = await r.json()
            assert r.status == 200
            assert doc["usage"]["completion_tokens"] == 4
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
