"""Benchmark harness tests: synthesizer structure + sweep over a live stack,
plus the suite's byte-accounting model (bench.py)."""

import asyncio

from dynamo_tpu.bench import SyntheticConfig, synthesize, sweep_http
from dynamo_tpu.bench.synthesizer import sharing_ratio


def test_decode_step_bytes_geometry():
    """The roofline byte model must follow the real layout: page-granular KV
    windows, untied embedding tables excluded from streamed weights (decode
    gathers rows, never the table), MLA rope stream lane-padded."""
    import bench
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]  # tie_embeddings=True
    params = llama.init_params(cfg, 0)
    total = bench.tree_nbytes(params)
    ps, batch, isl, osl = 8, 4, 10, 4
    got = bench.decode_step_bytes(params, cfg, batch, isl, osl, ps)
    # contexts 11..14 round to 16 pages-tokens each at page 8.
    per_tok = cfg.kv_bytes_per_token(itemsize=2)
    assert got == total + batch * 16 * per_tok

    # Untied: the embedding table is subtracted from streamed bytes.
    import dataclasses

    cfg2 = dataclasses.replace(cfg, tie_embeddings=False)
    params2 = llama.init_params(cfg2, 0)
    got2 = bench.decode_step_bytes(params2, cfg2, batch, isl, osl, ps)
    assert got2 == bench.tree_nbytes(params2) - bench.tree_nbytes(params2["embed"]) \
        + batch * 16 * per_tok

    # vs_roofline <= 1 by construction: the ceiling uses spec bandwidth.
    roof = bench.roofline_tok_per_sec(got, batch)
    assert roof == batch / (got / (bench.SPEC_HBM_GBPS * 1e9))

    # Every suite preset has a FIXED external anchor (self-graded rooflines
    # as targets were VERDICT r4 weak #3).
    for preset, *_ in bench.DEFAULT_SUITE:
        assert preset in bench.ANCHOR_TOK_PER_SEC


def test_stall_probe_structure(monkeypatch):
    """probe_decode_stall's contract: stable keys for both scheduling modes
    plus the ratio, sized down to a CPU-friendly scenario. The 5x acceptance
    ratio is a TPU bench claim, not asserted here — CPU step times are
    dominated by dispatch overhead, so only structure and counters are
    stable."""
    import bench

    monkeypatch.setenv("BENCH_STALL_PRESET", "test-tiny")
    monkeypatch.setenv("BENCH_STALL_DECODERS", "2")
    monkeypatch.setenv("BENCH_STALL_ISL", "8")
    monkeypatch.setenv("BENCH_STALL_OSL", "8")
    monkeypatch.setenv("BENCH_STALL_PREFILL_ISL", "48")
    monkeypatch.setenv("BENCH_STALL_CHUNK", "8")
    monkeypatch.setenv("BENCH_PAGE_SIZE", "4")
    out = bench.probe_decode_stall()
    assert out["preset"] == "test-tiny"
    for mode in ("chunked", "baseline_phase_exclusive"):
        run = out[mode]
        for key in ("chunk_prefill_tokens", "max_decode_stall_ms",
                    "decode_step_p50_ms", "itl_p50_ms", "itl_p99_ms",
                    "mixed_steps", "stall_violations", "steps"):
            assert key in run, f"{mode} missing {key}"
        assert run["steps"] > 0
        assert run["max_decode_stall_ms"] >= 0
    # The modes really did schedule differently.
    assert out["chunked"]["chunk_prefill_tokens"] == 8
    assert out["chunked"]["mixed_steps"] > 0
    assert out["chunked"]["stall_violations"] == 0
    assert out["baseline_phase_exclusive"]["mixed_steps"] == 0
    assert out["baseline_phase_exclusive"]["stall_violations"] > 0
    assert "stall_ratio_baseline_over_chunked" in out


def test_spec_probe_structure(monkeypatch):
    """probe_spec_decode's contract: stable keys for both modes plus the
    headline acceptance rate and speedup, sized down to CPU. The >1 speedup
    is a TPU bench claim — on CPU a verify dispatch costs more than the
    decode it replaces — so only structure, losslessness-adjacent token
    counts, and a positive acceptance rate are asserted."""
    import bench

    monkeypatch.setenv("BENCH_SPEC_PRESET", "test-tiny")
    monkeypatch.setenv("BENCH_SPEC_K", "4")
    monkeypatch.setenv("BENCH_SPEC_BATCH", "2")
    monkeypatch.setenv("BENCH_SPEC_ISL", "32")
    monkeypatch.setenv("BENCH_SPEC_OSL", "16")
    monkeypatch.setenv("BENCH_SPEC_CHUNK", "16")
    monkeypatch.setenv("BENCH_PAGE_SIZE", "4")
    out = bench.probe_spec_decode()
    assert out["preset"] == "test-tiny"
    for mode in ("spec", "baseline"):
        run = out[mode]
        for key in ("spec_k", "tok_per_sec", "decode_tokens", "decode_steps",
                    "spec_tokens_proposed", "spec_tokens_accepted",
                    "spec_accept_rate"):
            assert key in run, f"{mode} missing {key}"
        assert run["decode_steps"] > 0
    # Identical scenario in both modes: losslessness means identical totals.
    assert out["spec"]["decode_tokens"] == out["baseline"]["decode_tokens"]
    assert out["baseline"]["spec_tokens_proposed"] == 0
    # Repetitive prompts: the drafter must engage and land some tokens.
    assert out["spec"]["spec_tokens_proposed"] > 0
    assert out["spec"]["spec_accept_rate"] > 0
    # Accepted drafts shrink the step count for the same token total.
    assert out["spec"]["decode_steps"] < out["baseline"]["decode_steps"]
    assert out["spec_accept_rate"] == out["spec"]["spec_accept_rate"]
    assert "spec_decode_speedup" in out


def test_decode_kernel_probe_structure(monkeypatch):
    """probe_decode_kernel's contract (ISSUE 7): stable headline keys plus a
    per-cell grid, sized down to a CPU/interpret-friendly geometry. The
    bandwidth values are emulation artifacts off-TPU, so only structure and
    positivity are asserted."""
    import bench

    monkeypatch.setenv("BENCH_DK_BATCHES", "1,2")
    monkeypatch.setenv("BENCH_DK_CONTEXTS", "24,40")
    # conftest defaults the device-cost plane off for the suite; this test
    # asserts the probe's live_roofline_frac join, so opt back in.
    monkeypatch.setenv("DYN_COST_PLANE", "1")
    monkeypatch.setenv("BENCH_DK_PAGE_SIZE", "8")
    monkeypatch.setenv("BENCH_DK_HEADS", "4")
    monkeypatch.setenv("BENCH_DK_KV", "2")
    monkeypatch.setenv("BENCH_DK_HEAD_DIM", "16")
    monkeypatch.setenv("BENCH_DK_ITERS", "1")
    out = bench.probe_decode_kernel()
    assert out["interpret"] is True  # CPU-pinned suite
    assert "error" not in out
    assert len(out["grid"]) == 4  # 2 batches x 2 contexts
    for cell in out["grid"]:
        for key in ("batch", "context", "kv_bytes_per_call", "us_per_call",
                    "gbytes_per_sec", "roofline_frac"):
            assert key in cell, f"grid cell missing {key}"
        # KV read model: K and V, whole pages, bf16.
        pages = -(-cell["context"] // 8)
        assert cell["kv_bytes_per_call"] == 2 * cell["batch"] * pages * 8 * 32 * 2
        assert cell["gbytes_per_sec"] > 0
    assert out["decode_kernel_gbps"] == max(
        c["gbytes_per_sec"] for c in out["grid"])
    assert out["decode_roofline_frac"] > 0
    # Device-cost plane cross-check (ISSUE 19): the probe feeds its measured
    # cells through a CostRegistry, so the live-ledger fraction rides along.
    assert out["live_roofline_frac"] > 0


def test_slo_sched_probe_structure(monkeypatch):
    """probe_slo_sched's contract (ISSUE 9): identical mixed-tenant scenario
    under FIFO and under the SLO plane, stable keys for both modes, and the
    headline gain. Sized down but with the head-of-line blocking still
    decisive (two ~100 ms heavy prefills ahead of eight light requests on a
    150 ms TTFT budget: FIFO serves the first heavy in budget but blows it
    for every light), so EDF must beat FIFO on goodput even on CPU."""
    import bench

    monkeypatch.setenv("BENCH_SLOSCHED_HEAVY", "2")
    monkeypatch.setenv("BENCH_SLOSCHED_HEAVY_ISL", "2048")
    monkeypatch.setenv("BENCH_SLOSCHED_LIGHT", "8")
    monkeypatch.setenv("BENCH_SLOSCHED_LIGHT_ISL", "64")
    monkeypatch.setenv("BENCH_SLOSCHED_OSL", "8")
    monkeypatch.setenv("BENCH_SLOSCHED_TTFT_MS", "150")
    monkeypatch.setenv("BENCH_SLOSCHED_CHUNK", "256")
    out = bench.probe_slo_sched()
    assert out["ttft_slo_ms"] == 150.0
    assert out["heavy"] == {"n": 2, "isl": 2048}
    assert out["light"] == {"n": 8, "isl": 64}
    for mode in ("fifo", "slo_sched"):
        run = out[mode]
        for key in ("mode", "elapsed_s", "requests_met_ttft", "requests_total",
                    "goodput_tokens_per_s", "light_ttft_p50_ms",
                    "light_ttft_p99_ms", "deadline_misses", "throttle_events",
                    "tenant_throttled"):
            assert key in run, f"{mode} missing {key}"
        assert run["requests_total"] == 10
    # FIFO never consults the plane; the SLO run throttles the heavy tenant.
    assert out["fifo"]["throttle_events"] == 0
    assert out["slo_sched"]["throttle_events"] > 0
    assert out["slo_sched"]["tenant_throttled"].get("heavy", 0) > 0
    # The headline: same capacity, more SLO-attaining tokens, lights fast.
    assert out["slo_sched_goodput_gain"] > 1.0
    assert out["slo_sched"]["requests_met_ttft"] > out["fifo"]["requests_met_ttft"]
    assert 0 < out["slo_sched_ttft_p99_ms"] <= 150.0
    assert out["slo_sched_ttft_p99_ms"] == out["slo_sched"]["light_ttft_p99_ms"]


def test_overlap_probe_structure(monkeypatch):
    """probe_engine_overlap's contract (ISSUE 10): the same decode-heavy
    scenario under the synchronous loop and under the depth-1 overlapped
    pipeline, bit-identical streams, and the two headline numbers. Sized
    down, but with d2h latency comparable to compute so hiding it is
    decisive even on a loaded CI host."""
    import bench

    monkeypatch.setenv("BENCH_OVERLAP_DECODERS", "2")
    monkeypatch.setenv("BENCH_OVERLAP_ISL", "16")
    monkeypatch.setenv("BENCH_OVERLAP_OSL", "24")
    monkeypatch.setenv("BENCH_OVERLAP_DECODE_US", "1500")
    monkeypatch.setenv("BENCH_OVERLAP_D2H_US", "1200")
    monkeypatch.setenv("BENCH_OVERLAP_MIXED_DECODERS", "3")
    monkeypatch.setenv("BENCH_OVERLAP_MIXED_ISL", "96")
    monkeypatch.setenv("BENCH_OVERLAP_MIXED_OSL", "16")
    monkeypatch.setenv("BENCH_OVERLAP_MIXED_CHUNK", "32")
    monkeypatch.setenv("BENCH_OVERLAP_JSON_DECODERS", "2")
    monkeypatch.setenv("BENCH_OVERLAP_JSON_ISL", "16")
    monkeypatch.setenv("BENCH_OVERLAP_JSON_OSL", "24")
    out = bench.probe_engine_overlap()
    assert out["decoders"] == 2 and out["osl"] == 24
    for mode in ("sync", "overlap"):
        run = out[mode]
        for key in ("mode", "elapsed_s", "itl_mean_ms", "device_idle_frac",
                    "overlap_steps", "mean_gap_ms"):
            assert key in run, f"{mode} missing {key}"
    assert out["sync"]["mode"] == "sync"
    assert out["sync"]["overlap_steps"] == {"overlapped": 0, "barrier": 0}
    assert out["overlap"]["overlap_steps"]["overlapped"] > 0
    # The acceptance bar: same tokens, device idles strictly less, ITL gain.
    assert out["bit_identical"] is True
    assert out["overlap"]["device_idle_frac"] < out["sync"]["device_idle_frac"]
    assert out["device_idle_frac"] == out["overlap"]["device_idle_frac"]
    assert out["engine_overlap_itl_gain"] > 1.0
    # Mixed-traffic variant (ISSUE 11): staggered admission + chunked
    # prefill must ride the chained pipeline, not barrier it away.
    mixed = out["mixed"]
    assert mixed["bit_identical"] is True
    assert mixed["sync"]["overlap_steps"] == {"overlapped": 0, "barrier": 0}
    mo = mixed["overlap"]
    for key in ("mode", "elapsed_s", "itl_mean_ms", "overlap_steps",
                "barrier_reasons", "overlap_chained_frac"):
        assert key in mo, f"mixed overlap missing {key}"
    assert mo["overlap_steps"]["overlapped"] > 0
    assert out["overlap_chained_frac"] == mo["overlap_chained_frac"]
    assert out["overlap_chained_frac"] >= 0.9  # the ISSUE 11 acceptance bar
    assert out["engine_overlap_mixed_itl_gain"] > 0.0
    # Constrained variant (ISSUE 14): JSON-mode rows chain through the mask
    # lookahead instead of barriering every step, streams stay identical,
    # and the residual barriers are not constraint-shaped.
    con = out["constrained"]
    assert con["bit_identical"] is True
    base, la = con["no_lookahead"], con["lookahead_on"]
    for key in ("mode", "elapsed_s", "itl_mean_ms", "overlap_steps",
                "barrier_reasons", "overlap_barrier_frac",
                "mask_cache_hits", "mask_cache_misses"):
        assert key in base and key in la, f"constrained run missing {key}"
    assert base["overlap_steps"]["overlapped"] == 0
    assert base["barrier_reasons"].get("constraint", 0) > 0
    assert la["overlap_steps"]["overlapped"] > 0
    assert la["barrier_reasons"].get("constraint", 0) == 0
    assert la["overlap_barrier_frac"] < base["overlap_barrier_frac"] == 1.0
    assert out["overlap_barrier_frac"] == la["overlap_barrier_frac"]
    assert out["overlap_constrained_itl_gain"] > 0.0
    assert la["mask_cache_hits"] > 0


def test_bench_doc_goodput_keys():
    """build_doc's top-level contract (ISSUE 4): the SLO-conditioned goodput
    headline keys are stable, sourced from the headline (llama-3.2-1b)
    config, and default to 0.0 when the suite produced nothing usable."""
    import bench

    configs = [
        {"preset": "test-tiny", "tok_per_sec": 5.0,
         "slo_ttft_attainment": 1.0, "goodput_tokens_per_s_at_slo": 5.0},
        {"preset": "llama-3.2-1b", "tok_per_sec": 100.0, "slo_ttft_ms": 500.0,
         "slo_ttft_attainment": 0.9, "goodput_tokens_per_s_at_slo": 90.0},
    ]
    doc = bench.build_doc(configs, pull={"skipped": True})
    assert doc["goodput_tokens_per_s_at_slo"] == 90.0  # headline, not first
    assert doc["slo_ttft_attainment"] == 0.9
    assert doc["value"] == 100.0
    assert doc["itl_p99_ms"] == 0.0  # stall probe absent: stable default
    assert doc["spec_accept_rate"] == 0.0  # spec probe absent: stable default
    spec = {"spec_accept_rate": 0.6, "spec_decode_speedup": 1.8}
    doc2 = bench.build_doc(configs, pull={}, spec=spec)
    assert doc2["spec_accept_rate"] == 0.6
    assert doc2["spec_decode_speedup"] == 1.8
    assert doc2["decode_kernel_gbps"] == 0.0  # probe absent: stable default
    dk = {"decode_kernel_gbps": 700.5, "decode_roofline_frac": 0.8553,
          "live_roofline_frac": 0.8101}
    doc3 = bench.build_doc(configs, pull={}, decode_kernel=dk)
    assert doc3["decode_kernel_gbps"] == 700.5
    assert doc3["decode_roofline_frac"] == 0.8553
    # Device-cost plane headline (ISSUE 19): headline-config value wins,
    # kernel-probe value is the fallback.
    assert doc3["live_roofline_frac"] == 0.8101
    assert doc3["detail"]["decode_kernel_probe"] == dk
    assert doc3["kv_wire_gbps"] == 0.0  # wire sweep absent: stable default
    # KV-wire headline keys (ISSUE 8) surface from the sweep dict.
    wire = {"kv_wire_gbps": 2.375, "kv_wire_overlap_frac": 0.41,
            "speedup_vs_v2": 6.2, "sweep": []}
    doc4 = bench.build_doc(configs, pull={}, wire=wire)
    assert doc4["kv_wire_gbps"] == 2.375
    assert doc4["kv_wire_overlap_frac"] == 0.41
    assert doc4["detail"]["kv_wire_cross_process"] == wire
    assert doc4["slo_sched_goodput_gain"] == 0.0  # probe absent: stable default
    # SLO admission headline keys (ISSUE 9) surface from the probe dict.
    ss = {"slo_sched_goodput_gain": 5.4869, "slo_sched_ttft_p99_ms": 105.31}
    doc5 = bench.build_doc(configs, pull={}, slo_sched=ss)
    assert doc5["slo_sched_goodput_gain"] == 5.4869
    assert doc5["slo_sched_ttft_p99_ms"] == 105.31
    assert doc5["detail"]["slo_sched_probe"] == ss
    assert doc5["engine_overlap_itl_gain"] == 0.0  # probe absent: stable default
    # Overlapped-execution headline keys (ISSUE 10) surface from the probe.
    ov = {"engine_overlap_itl_gain": 1.7523, "device_idle_frac": 0.0508,
          "bit_identical": True, "overlap_chained_frac": 0.9412,
          "engine_overlap_mixed_itl_gain": 1.31,
          "overlap_constrained_itl_gain": 1.654, "overlap_barrier_frac": 0.115}
    doc6 = bench.build_doc(configs, pull={}, overlap=ov)
    assert doc6["engine_overlap_itl_gain"] == 1.7523
    assert doc6["device_idle_frac"] == 0.0508
    # Always-on overlap headline keys (ISSUE 11) surface from the probe.
    assert doc6["overlap_chained_frac"] == 0.9412
    assert doc6["engine_overlap_mixed_itl_gain"] == 1.31
    assert doc5["overlap_chained_frac"] == 0.0  # probe absent: stable default
    # Chained constrained decode headline keys (ISSUE 14).
    assert doc6["overlap_constrained_itl_gain"] == 1.654
    assert doc6["overlap_barrier_frac"] == 0.115
    assert doc5["overlap_constrained_itl_gain"] == 0.0  # probe absent
    assert doc5["overlap_barrier_frac"] == 0.0
    assert doc6["detail"]["engine_overlap_probe"] == ov
    # An all-errors suite still emits the full key set.
    empty = bench.build_doc([{"preset": "x", "error": "boom"}], pull={})
    for key in ("value", "goodput_tokens_per_s_at_slo", "slo_ttft_attainment",
                "itl_p99_ms", "max_decode_stall_ms", "spec_accept_rate",
                "spec_decode_speedup", "decode_kernel_gbps",
                "decode_roofline_frac", "kv_wire_gbps",
                "kv_wire_overlap_frac", "slo_sched_goodput_gain",
                "slo_sched_ttft_p99_ms", "engine_overlap_itl_gain",
                "device_idle_frac", "live_roofline_frac"):
        assert key in empty
        assert empty[key] == 0.0


def test_bench_doc_prefix_reuse_keys():
    """Cache-aware serving headline keys (ISSUE 12): the prefix-reuse probe
    surfaces stable top-level keys and a detail record; absent probe emits
    0.0 defaults so the doc schema never shifts."""
    import bench

    configs = [{"preset": "test-tiny", "tok_per_sec": 5.0}]
    doc = bench.build_doc(configs, pull={})
    assert doc["prefix_reuse_ttft_gain"] == 0.0
    assert doc["prefix_onboard_overlap_frac"] == 0.0
    assert doc["detail"]["prefix_reuse_probe"] == {"pending": True}
    pr = {"prefix_reuse_ttft_gain": 55.04, "prefix_onboard_overlap_frac": 1.0,
          "cold": {"ttft_p50_ms": 212.46}, "reuse": {"ttft_p50_ms": 3.86}}
    doc2 = bench.build_doc(configs, pull={}, prefix_reuse=pr)
    assert doc2["prefix_reuse_ttft_gain"] == 55.04
    assert doc2["prefix_onboard_overlap_frac"] == 1.0
    assert doc2["detail"]["prefix_reuse_probe"] == pr


def test_bench_doc_fleet_sim_keys():
    """Fleet-sim headline keys (ISSUE 13): probe_fleet_sim surfaces stable
    top-level goodput/fairness keys and a detail record; absent probe emits
    0.0 defaults so the doc schema never shifts."""
    import bench

    configs = [{"preset": "test-tiny", "tok_per_sec": 5.0}]
    doc = bench.build_doc(configs, pull={})
    assert doc["fleet_goodput_frac_at_slo"] == 0.0
    assert doc["fleet_tenant_fairness"] == 0.0
    assert doc["detail"]["fleet_sim_probe"] == {"pending": True}
    fl = {"scenario": "smoke", "trace_digest": "abc", "digest_stable": True,
          "fleet_goodput_frac_at_slo": 0.92, "fleet_tenant_fairness": 0.88,
          "passed": True}
    doc2 = bench.build_doc(configs, pull={}, fleet=fl)
    assert doc2["fleet_goodput_frac_at_slo"] == 0.92
    assert doc2["fleet_tenant_fairness"] == 0.88
    assert doc2["detail"]["fleet_sim_probe"] == fl
    # A probe that errored keeps the stable defaults.
    doc3 = bench.build_doc(configs, pull={}, fleet={"error": "boom"})
    assert doc3["fleet_goodput_frac_at_slo"] == 0.0
    assert doc3["fleet_tenant_fairness"] == 0.0


def test_bench_doc_quant_and_mask_keys():
    """Roofline burn-down keys (ISSUE 16): the quant-mode sweep and the
    vectorized-mask probe surface stable `_gain`/`_ms` suffixed keys (so
    tools/bench_regress.py derives direction without a schema change) and
    detail records; absent probes keep 0.0 defaults."""
    import bench

    configs = [{"preset": "test-tiny", "tok_per_sec": 5.0}]
    doc = bench.build_doc(configs, pull={})
    for key in ("quant_int8_decode_gain", "quant_int4_decode_gain",
                "quant_int4_vs_int8_decode_gain", "constraint_mask_build_ms",
                "constraint_mask_build_gain"):
        assert doc[key] == 0.0
    assert doc["detail"]["quant_sweep_probe"] == {"pending": True}
    assert doc["detail"]["mask_build_probe"] == {"pending": True}

    qs = {"preset": "mla-8b-proxy", "bf16_basis": "modeled_from_int4_achieved_bw",
          "quant_int8_decode_gain": 1.9, "quant_int4_decode_gain": 3.1,
          "quant_int4_vs_int8_decode_gain": 1.63}
    mb = {"vocab": 128000, "mismatches": 0,
          "constraint_mask_build_ms": 30.7, "constraint_mask_build_gain": 16.9}
    doc2 = bench.build_doc(configs, pull={}, quant_sweep=qs, mask_build=mb)
    assert doc2["quant_int8_decode_gain"] == 1.9
    assert doc2["quant_int4_decode_gain"] == 3.1
    assert doc2["quant_int4_vs_int8_decode_gain"] == 1.63
    assert doc2["constraint_mask_build_ms"] == 30.7
    assert doc2["constraint_mask_build_gain"] == 16.9
    assert doc2["detail"]["quant_sweep_probe"] == qs
    assert doc2["detail"]["mask_build_probe"] == mb


def test_synthesizer_prefix_structure():
    cfg = SyntheticConfig(num_requests=32, shared_prefix_len=16, num_groups=3,
                          group_prefix_len=8, unique_len=4, osl_mean=20, seed=7)
    reqs = synthesize(cfg)
    assert len(reqs) == 32
    shared = reqs[0].token_ids[:16]
    groups = {}
    for r in reqs:
        assert r.token_ids[:16] == shared  # corpus-wide prefix
        assert len(r.token_ids) == 16 + 8 + 4
        groups.setdefault(r.group, r.token_ids[16:24])
        assert r.token_ids[16:24] == groups[r.group]  # group prefix stable
        assert 1 <= r.max_tokens <= 80
    assert len(groups) == 3
    # Different groups have different prefixes (overwhelmingly likely).
    assert len({tuple(g) for g in groups.values()}) == 3
    assert abs(sharing_ratio(cfg) - 24 / 28) < 1e-9


def test_synthesizer_deterministic():
    a = synthesize(SyntheticConfig(seed=3))
    b = synthesize(SyntheticConfig(seed=3))
    assert [r.token_ids for r in a] == [r.token_ids for r in b]
    assert [r.token_ids for r in a] != [r.token_ids for r in synthesize(SyntheticConfig(seed=4))]


async def test_sweep_over_live_stack():
    """Closed-loop sweep against a real served stack (mock engine): pareto
    rows come back populated and error-free."""
    from dynamo_tpu.launch import run_local

    handles = await run_local("test-tiny", port=0, mock=True, num_pages=512, max_batch_size=16)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        workload = synthesize(SyntheticConfig(num_requests=8, shared_prefix_len=16,
                                              group_prefix_len=8, unique_len=8, osl_mean=12))
        stats = await sweep_http(base, "test-tiny", workload, levels=[1, 4])
        assert [s.concurrency for s in stats] == [1, 4]
        for s in stats:
            assert s.errors == 0
            assert s.requests == 8
            assert s.output_tokens > 0
            assert s.output_tok_per_sec > 0
            assert s.ttft_p50 > 0
            assert s.ttft_p99 >= s.ttft_p50
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
