"""Benchmark harness tests: synthesizer structure + sweep over a live stack."""

import asyncio

from dynamo_tpu.bench import SyntheticConfig, synthesize, sweep_http
from dynamo_tpu.bench.synthesizer import sharing_ratio


def test_synthesizer_prefix_structure():
    cfg = SyntheticConfig(num_requests=32, shared_prefix_len=16, num_groups=3,
                          group_prefix_len=8, unique_len=4, osl_mean=20, seed=7)
    reqs = synthesize(cfg)
    assert len(reqs) == 32
    shared = reqs[0].token_ids[:16]
    groups = {}
    for r in reqs:
        assert r.token_ids[:16] == shared  # corpus-wide prefix
        assert len(r.token_ids) == 16 + 8 + 4
        groups.setdefault(r.group, r.token_ids[16:24])
        assert r.token_ids[16:24] == groups[r.group]  # group prefix stable
        assert 1 <= r.max_tokens <= 80
    assert len(groups) == 3
    # Different groups have different prefixes (overwhelmingly likely).
    assert len({tuple(g) for g in groups.values()}) == 3
    assert abs(sharing_ratio(cfg) - 24 / 28) < 1e-9


def test_synthesizer_deterministic():
    a = synthesize(SyntheticConfig(seed=3))
    b = synthesize(SyntheticConfig(seed=3))
    assert [r.token_ids for r in a] == [r.token_ids for r in b]
    assert [r.token_ids for r in a] != [r.token_ids for r in synthesize(SyntheticConfig(seed=4))]


async def test_sweep_over_live_stack():
    """Closed-loop sweep against a real served stack (mock engine): pareto
    rows come back populated and error-free."""
    from dynamo_tpu.launch import run_local

    handles = await run_local("test-tiny", port=0, mock=True, num_pages=512, max_batch_size=16)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        workload = synthesize(SyntheticConfig(num_requests=8, shared_prefix_len=16,
                                              group_prefix_len=8, unique_len=8, osl_mean=12))
        stats = await sweep_http(base, "test-tiny", workload, levels=[1, 4])
        assert [s.concurrency for s in stats] == [1, 4]
        for s in stats:
            assert s.errors == 0
            assert s.requests == 8
            assert s.output_tokens > 0
            assert s.output_tok_per_sec > 0
            assert s.ttft_p50 > 0
            assert s.ttft_p99 >= s.ttft_p50
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
