"""Expert-parallel MoE dispatch vs the dense golden formulation.

The capacity-dispatched path (parallel/moe.py) must be numerically
equivalent to dense compute when capacity admits every (token, choice), must
degrade gracefully (zero contribution) when it doesn't, and must produce the
same logits when the expert axis is sharded over the 8-device virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh
from dynamo_tpu.parallel.moe import expert_capacity, moe_mlp
from dynamo_tpu.parallel.sharding import shard_params

CFG = PRESETS["test-tiny-moe"]
PARAMS = llama.init_params(CFG, 0)
LP0 = jax.tree.map(lambda x: x[0], PARAMS["layers"])  # layer 0 slice


def _x(n, seed=0):
    return jnp.asarray(np.random.default_rng(seed).standard_normal((n, CFG.hidden_size)), jnp.float32)


def _dense(x):
    out = llama._mlp_moe_dense(LP0, x[None], CFG)
    return out[0]


def test_dispatched_matches_dense_with_nodrop_capacity():
    x = _x(24)
    got = moe_mlp(
        LP0, x, num_experts_per_token=CFG.num_experts_per_token,
        capacity=24 * CFG.num_experts_per_token,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(_dense(x)), rtol=1e-5, atol=1e-5)


def test_default_capacity_matches_when_balanced():
    # With capacity_factor headroom and a random router, drops are rare at
    # this size; verify the default path stays close to dense.
    x = _x(64, seed=1)
    got = moe_mlp(LP0, x, num_experts_per_token=CFG.num_experts_per_token, capacity_factor=4.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_dense(x)), rtol=1e-5, atol=1e-5)


def test_overflow_drops_are_finite_and_bounded():
    x = _x(32, seed=2)
    got = np.asarray(moe_mlp(LP0, x, num_experts_per_token=CFG.num_experts_per_token, capacity=8))
    assert np.isfinite(got).all()
    # Dropped rows lose contributions; no row should exceed the dense one by
    # more than fp noise (combine weights are a subset).
    dense = np.abs(np.asarray(_dense(x))).sum()
    assert np.abs(got).sum() <= dense * 1.01


def test_expert_capacity_bounds():
    assert expert_capacity(32, 4, 2, 1.0) == 16
    assert expert_capacity(32, 4, 2, 100.0) == 64  # clamped to N*k
    assert expert_capacity(8, 64, 2, 1.0) == 8  # floor at k, aligned up


def test_moe_forward_sharded_ep_matches_single_device():
    plan = MeshPlan.auto(8, num_kv_heads=CFG.num_kv_heads, num_experts=CFG.num_experts)
    assert plan.ep > 1, plan
    mesh = make_mesh(plan, jax.devices())

    b, t = 2, 8
    tokens = jnp.asarray(np.random.default_rng(3).integers(0, CFG.vocab_size, (b, t)), jnp.int32)
    positions = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None], (b, 1))
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    ps = 4
    slots = jnp.take_along_axis(tables, positions // ps, axis=1) * ps + positions % ps
    last = jnp.full((b,), t - 1, jnp.int32)

    def fwd(p):
        kc, vc = llama.init_kv_cache(CFG, num_pages=8, page_size=ps)
        logits, _, _ = llama.forward(
            p, CFG, tokens, positions, kc, vc, tables, slots, last,
            attn_impl="reference", mesh=mesh,
        )
        return logits

    # The mesh must be threaded exactly as the serving runner does: it is
    # what routes _mlp_moe onto the capacity dispatch under an ep axis. The
    # dropless ragged_dot path is NOT ep-shardable — GSPMD mis-partitions the
    # group axis when the expert weights are sharded, producing wrong logits
    # rather than an error (max abs diff ~1.3 on this tiny config).
    want = np.asarray(fwd(PARAMS))
    placed = shard_params(PARAMS, mesh)
    got = np.asarray(jax.jit(fwd)(placed))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_dropless_matches_dense():
    from dynamo_tpu.parallel.moe import moe_mlp_dropless

    x = _x(48, seed=4)
    got = moe_mlp_dropless(LP0, x, num_experts_per_token=CFG.num_experts_per_token)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_dense(x)), rtol=1e-5, atol=1e-5)


def test_shared_expert_and_bias_forward():
    """Shared-expert MoE + qkv-bias forward runs and the shared branch
    contributes (outputs differ from the routed-only model)."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, shared_expert_size=32, shared_expert_gated=True, attention_bias=True,
    )
    params = llama.init_params(cfg, 7)
    b, t, ps = 1, 4, 4
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    positions = jnp.arange(t, dtype=jnp.int32)[None]
    tables = jnp.asarray([[1]], jnp.int32)
    slots = positions + ps
    last = jnp.asarray([t - 1], jnp.int32)

    def fwd(p, c):
        kc, vc = llama.init_kv_cache(c, num_pages=4, page_size=ps)
        return llama.forward(p, c, tokens, positions, kc, vc, tables, slots, last,
                             attn_impl="reference")[0]

    out = np.asarray(fwd(params, cfg))
    assert np.isfinite(out).all()
    # Zeroing the shared expert changes the logits.
    p2 = {**params, "layers": {**params["layers"], "w_shared_down": params["layers"]["w_shared_down"] * 0}}
    out2 = np.asarray(fwd(p2, cfg))
    assert not np.allclose(out, out2)


def test_over_capacity_degrades_gracefully_exact():
    """At over-capacity the output must equal a reference that applies the
    SAME drop rule (token-major priority per expert): surviving choices keep
    their exact routing weights, dropped choices contribute exactly zero —
    not a renormalized or corrupted mix (VERDICT r3 weak #7)."""
    from dynamo_tpu.parallel.moe import route_tokens

    n, c = 32, 8  # force drops: balanced load would need N*k/E slots
    x = _x(n, seed=5)
    k = CFG.num_experts_per_token
    got = np.asarray(moe_mlp(LP0, x, num_experts_per_token=k, capacity=c))

    # Reference: dense per-(token, choice) expert outputs combined with the
    # dispatch's drop rule re-derived independently.
    weights, topi = route_tokens(LP0, x, k=k)
    weights, topi = np.asarray(weights), np.asarray(topi)
    e = LP0["router"].shape[-1]
    seen = {ei: 0 for ei in range(e)}
    expected = np.zeros((n, x.shape[-1]), np.float32)
    dropped = 0
    for t in range(n):
        for j in range(k):
            ei = int(topi[t, j])
            if seen[ei] < c:
                seen[ei] += 1
                xe = np.asarray(_expert_forward(LP0, x[t : t + 1], ei))
                expected[t] += weights[t, j] * xe[0]
            else:
                dropped += 1
    assert dropped > 0, "test must actually exercise the drop path"
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def _expert_forward(lp, xt, ei):
    import jax.numpy as jnp

    gate = jax.nn.silu(xt @ lp["w_gate"][ei])
    up = xt @ lp["w_up"][ei]
    return np.asarray((gate * up) @ lp["w_down"][ei], np.float32)


def test_drop_counter_feeds_serving_metrics(monkeypatch):
    """A forced over-capacity SERVING step must increment the process drop
    counter, which EngineCore.metrics() reports as ForwardPassMetrics.moe_*
    and the fleet Prometheus exporter exposes on /metrics (VERDICT r4
    weak #4 — observability that actually observes)."""
    import dataclasses
    from types import SimpleNamespace

    from dynamo_tpu.deploy.metrics_service import MetricsService
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.parallel.moe import DROP_COUNTER
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    # Force the capacity dispatch with a squeezed capacity factor: 24 prompt
    # tokens * k=2 = 48 choices into 4 experts * capacity 8 = 32 slots, so
    # prefill must drop >= 16 choices regardless of routing balance.
    monkeypatch.setenv("DYNAMO_MOE_DISPATCH", "capacity")
    cfg = dataclasses.replace(CFG, moe_capacity_factor=0.5)
    params = llama.init_params(cfg, 11)
    page = 4
    runner = ModelRunner(
        cfg, params, num_pages=32, page_size=page, max_batch_size=4,
        prefill_bucket=32, attn_impl="reference",
    )
    core = EngineCore(
        runner,
        EngineConfig(num_pages=32, page_size=page, max_batch_size=4,
                     max_prefill_tokens=64, max_seq_len=64),
    )
    DROP_COUNTER.reset()
    core.add_request(
        PreprocessedRequest(
            token_ids=list(range(2, 26)),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=2),
        ),
        Context(),
    )
    while core.has_work:
        core.step()
    jax.effects_barrier()  # debug callbacks are async; flush before reading

    m = core.metrics()
    assert m.moe_choices_total > 0
    assert m.moe_dropped_total > 0, "over-capacity step must record drops"
    assert m.moe_dropped_total <= m.moe_choices_total
    d = m.to_dict()
    assert d["moe_dropped_total"] == m.moe_dropped_total

    svc = MetricsService.__new__(MetricsService)
    svc.aggregator = SimpleNamespace(snapshot=lambda: {m.worker_id: m})
    text = svc.render()
    line = f'dynamo_worker_moe_dropped_total{{worker_id="{m.worker_id:x}"}} {m.moe_dropped_total}'
    assert line in text, text


def test_drop_fraction_estimator():
    """moe_drop_stats: the serving-side observability hook for capacity
    dispatch — reports (total choices, dropped) for a routing batch so
    operators can alarm on drop rate without instrumenting the jit."""
    from dynamo_tpu.parallel.moe import moe_drop_stats

    x = _x(32, seed=6)
    total, dropped = moe_drop_stats(
        LP0, x, num_experts_per_token=CFG.num_experts_per_token, capacity=8
    )
    assert total == 32 * CFG.num_experts_per_token
    assert 0 < dropped < total
    # No-drop capacity reports zero.
    total2, dropped2 = moe_drop_stats(
        LP0, x, num_experts_per_token=CFG.num_experts_per_token,
        capacity=32 * CFG.num_experts_per_token,
    )
    assert dropped2 == 0
