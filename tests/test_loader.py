"""Checkpoint loader round-trip: params -> HF safetensors dir -> params.

``save_params`` emits the exact HF layout (torch [out, in] orientation,
per-layer tensor names), so loading it back through the HF name mapping and
comparing forwards proves the loader against the real checkpoint format.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS, ModelConfig
from dynamo_tpu.models.loader import load_model, load_params, save_params


def _assert_trees_equal(a, b, path=""):
    assert set(a) == set(b), f"{path}: {set(a)} != {set(b)}"
    for k in a:
        if isinstance(a[k], dict):
            _assert_trees_equal(a[k], b[k], f"{path}/{k}")
        else:
            np.testing.assert_allclose(
                np.asarray(a[k], np.float32), np.asarray(b[k], np.float32),
                err_msg=f"{path}/{k}", rtol=0, atol=0,
            )


def test_roundtrip_dense(tmp_path):
    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    save_params(tmp_path, cfg, params)
    cfg2, loaded = load_model(tmp_path, name=cfg.name, dtype=cfg.dtype)
    assert cfg2.hidden_size == cfg.hidden_size
    assert cfg2.num_kv_heads == cfg.num_kv_heads
    assert cfg2.tie_embeddings == cfg.tie_embeddings
    _assert_trees_equal(params, loaded)


def test_roundtrip_untied_lm_head(tmp_path):
    cfg = dataclasses.replace(PRESETS["test-tiny"], tie_embeddings=False)
    params = llama.init_params(cfg, 1)
    save_params(tmp_path, cfg, params)
    _cfg2, loaded = load_model(tmp_path, dtype=cfg.dtype)
    _assert_trees_equal(params, loaded)


def test_roundtrip_moe(tmp_path):
    cfg = PRESETS["test-tiny-moe"]
    params = llama.init_params(cfg, 2)
    save_params(tmp_path, cfg, params)
    cfg2 = ModelConfig.from_hf(tmp_path / "config.json", name=cfg.name)
    cfg2 = dataclasses.replace(
        cfg2,
        num_experts=cfg.num_experts,
        num_experts_per_token=cfg.num_experts_per_token,
        moe_intermediate_size=cfg.moe_intermediate_size,
        dtype=cfg.dtype,
    )
    loaded = load_params(tmp_path, cfg2)
    _assert_trees_equal(params, loaded)


def test_sharded_load_matches_unsharded(tmp_path):
    """Direct-to-mesh placement must produce the same values as host load."""
    cfg = dataclasses.replace(PRESETS["test-tiny"], num_kv_heads=2, head_dim=64, num_heads=4)
    params = llama.init_params(cfg, 3)
    save_params(tmp_path, cfg, params)

    from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan.auto(8, num_kv_heads=cfg.num_kv_heads))
    loaded = load_params(tmp_path, cfg, mesh=mesh)
    _assert_trees_equal(params, jax.tree.map(lambda x: np.asarray(x), loaded))
    # Spot-check an actually-sharded leaf's sharding.
    wq = loaded["layers"]["wq"]
    assert not wq.sharding.is_fully_replicated


def test_forward_equivalence_after_load(tmp_path):
    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 4)
    save_params(tmp_path, cfg, params)
    _cfg, loaded = load_model(tmp_path, dtype=cfg.dtype)

    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    tables = jnp.asarray([[1, 2]], jnp.int32)
    slots = positions + 4  # page 1 starts at slot 4 (page_size 4)
    last = jnp.asarray([3], jnp.int32)

    def fwd(p):
        kc, vc = llama.init_kv_cache(cfg, num_pages=4, page_size=4)
        logits, _, _ = llama.forward(
            p, cfg, tokens, positions, kc, vc, tables, slots, last,
            attn_impl="reference",
        )
        return logits

    np.testing.assert_allclose(np.asarray(fwd(params)), np.asarray(fwd(loaded)), rtol=1e-6, atol=1e-6)


def test_roundtrip_gguf_sourced_gemma(tmp_path):
    """GGUF-sourced Gemma params arrive with norm_plus_one=False (+1 baked
    into the norm weights by llama.cpp) but still gelu_tanh + embed_scale.
    save_params must (a) still stamp model_type=gemma — keyed off ANY of the
    three family markers, not just norm_plus_one — and (b) zero-center the
    norms, so the reload (runtime re-adds the +1) computes the same math."""
    cfg = dataclasses.replace(
        PRESETS["test-tiny"], mlp_act="gelu_tanh", embed_scale=True,
        norm_plus_one=False,
    )
    params = llama.init_params(cfg, 5)
    save_params(tmp_path, cfg, params)
    cfg2, loaded = load_model(tmp_path, name=cfg.name, dtype=cfg.dtype)
    # The reload takes the HF-convention Gemma shape...
    assert cfg2.norm_plus_one and cfg2.embed_scale and cfg2.mlp_act == "gelu_tanh"
    # ...with re-centered norms: loaded + 1 == the baked-in originals.
    for got, want in [
        (loaded["norm_f"], params["norm_f"]),
        (loaded["layers"]["attn_norm"], params["layers"]["attn_norm"]),
        (loaded["layers"]["mlp_norm"], params["layers"]["mlp_norm"]),
    ]:
        np.testing.assert_allclose(
            np.asarray(got, np.float32) + 1.0, np.asarray(want, np.float32),
            rtol=0, atol=1e-6,
        )
    # Non-norm leaves pass through untouched.
    np.testing.assert_array_equal(
        np.asarray(loaded["embed"]), np.asarray(params["embed"]))

    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    positions = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    tables = jnp.asarray([[1, 2]], jnp.int32)
    slots = positions + 4
    last = jnp.asarray([3], jnp.int32)

    def fwd(p, c):
        kc, vc = llama.init_kv_cache(c, num_pages=4, page_size=4)
        logits, _, _ = llama.forward(
            p, c, tokens, positions, kc, vc, tables, slots, last,
            attn_impl="reference",
        )
        return np.asarray(logits)

    # Same function either way: baked norms w/o +1 == centered norms w/ +1.
    np.testing.assert_allclose(fwd(params, cfg), fwd(loaded, cfg2), rtol=2e-5, atol=2e-5)


def make_model_dir(tmp_path, cfg=None, seed=7):
    """A complete hermetic HF-style model dir: weights + tokenizer + template."""
    import json

    from tokenizers import Tokenizer, models as tok_models

    cfg = cfg or PRESETS["test-tiny"]
    params = llama.init_params(cfg, seed)
    save_params(tmp_path, cfg, params)
    # Character-level BPE (no merges): hermetic, deterministic, real format.
    charset = [chr(c) for c in range(32, 127)]
    vocab = {"<unk>": 0, "<eos>": 1}
    for ch in charset:
        vocab[ch] = len(vocab)
    tok = Tokenizer(tok_models.BPE(vocab=vocab, merges=[], unk_token="<unk>"))
    tok.save(str(tmp_path / "tokenizer.json"))
    hf_cfg = json.loads((tmp_path / "config.json").read_text())
    hf_cfg["eos_token_id"] = 1
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))
    (tmp_path / "tokenizer_config.json").write_text(json.dumps({
        "chat_template": "{% for m in messages %}{{ m['content'] }}{% endfor %}",
    }))
    return params


async def test_serve_model_dir_end_to_end(tmp_path):
    """run_local on a checkpoint directory: weights, tokenizer, chat template
    and eos ids all come from the dir; generation round-trips over HTTP."""
    import aiohttp

    from dynamo_tpu.launch import run_local

    make_model_dir(tmp_path)
    handles = await run_local(str(tmp_path), port=0, num_pages=64, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(base + "/v1/models") as r:
                assert (await r.json())["data"][0]["id"] == tmp_path.name
            body = {
                "model": tmp_path.name,
                "messages": [{"role": "user", "content": "hello world"}],
                "max_tokens": 6,
                "temperature": 0,
            }
            async with s.post(base + "/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
                text = out["choices"][0]["message"]["content"]
                assert isinstance(text, str)
                # Tokens decode through the real tokenizer: printable chars only.
                assert all(32 <= ord(c) < 127 for c in text), repr(text)
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


def test_roundtrip_qwen2_moe_shared_expert_and_bias(tmp_path):
    """Qwen2-MoE layout: routed experts + gated shared expert + qkv biases."""
    cfg = dataclasses.replace(
        PRESETS["test-tiny-moe"],
        shared_expert_size=32, shared_expert_gated=True, attention_bias=True,
    )
    params = llama.init_params(cfg, 5)
    assert "w_shared_gate" in params["layers"] and "bq" in params["layers"]
    save_params(tmp_path, cfg, params)
    cfg2, loaded = load_model(tmp_path, dtype=cfg.dtype)
    assert cfg2.shared_expert_size == 32 and cfg2.shared_expert_gated and cfg2.attention_bias
    _assert_trees_equal(params, loaded)


def test_strict_load_rejects_dropped_tensors(tmp_path):
    """A checkpoint with tensors the mapping would ignore must fail loudly."""
    import dataclasses as dc

    cfg = dataclasses.replace(
        PRESETS["test-tiny-moe"],
        shared_expert_size=32, shared_expert_gated=True,
    )
    params = llama.init_params(cfg, 6)
    save_params(tmp_path, cfg, params)
    # Load with a config that doesn't know about the shared expert: its
    # tensors would be silently dropped -> strict mode must raise.
    bad_cfg = dc.replace(cfg, shared_expert_size=0, shared_expert_gated=False)
    with pytest.raises(ValueError, match="silently drop"):
        load_params(tmp_path, bad_cfg)
    # Explicit opt-out still works.
    load_params(tmp_path, bad_cfg, strict=False)


def test_bare_deepseek_v3_config_defaults_sigmoid_scoring():
    """Native transformers DeepseekV3Config doesn't serialize scoring_func
    (its modeling hardcodes sigmoid routing); a bare config.json must parse
    to sigmoid scoring + router bias via the model_type fallback — same gap
    as moe_router_bias (ADVICE r3 medium)."""
    bare = {
        "model_type": "deepseek_v3",
        "vocab_size": 256, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 3, "num_attention_heads": 4,
        "num_key_value_heads": 4, "q_lora_rank": 32, "kv_lora_rank": 24,
        "qk_nope_head_dim": 16, "qk_rope_head_dim": 8, "v_head_dim": 16,
        "first_k_dense_replace": 1, "n_routed_experts": 4,
        "num_experts_per_tok": 2, "moe_intermediate_size": 32,
        # NO scoring_func, NO topk_method, NO norm_topk_prob keys.
    }
    cfg = ModelConfig.from_hf(bare)
    assert cfg.moe_scoring == "sigmoid"
    assert cfg.moe_router_bias is True
    assert cfg.moe_norm_topk is True
    # Non-V3 MoE without the key still defaults to softmax.
    qwen = dict(bare, model_type="qwen2_moe", kv_lora_rank=None,
                q_lora_rank=None, first_k_dense_replace=0)
    assert ModelConfig.from_hf(qwen).moe_scoring == "softmax"
