"""Observability plane: distributed trace propagation, EngineMetrics,
/debug/traces timeline assembly, /metrics federation, metric-name hygiene.

Covers the ISSUE 3 tentpole end to end: a TraceContext minted at the edge
rides runtime hops (real TCP), spans from every process land in the ring
buffer under one trace_id, the frontend assembles them into one timeline,
and the engine registries federate into the frontend's /metrics render.
"""

import asyncio
import json
import pathlib
import sys
import time
from types import SimpleNamespace
from typing import Any, AsyncIterator

import aiohttp
import pytest

from dynamo_tpu.observability.metrics import KV_PHASES, EngineMetrics, federate_text
from dynamo_tpu.observability.service import assemble_timeline
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.discovery import MemoryStore
from dynamo_tpu.runtime.engine import AsyncEngine, Context, collect
from dynamo_tpu.runtime.tcp import TcpTransport
from dynamo_tpu.tracing import SPANS, Span, TraceContext, trace_of


# -- trace identity -----------------------------------------------------------


def test_traceparent_roundtrip():
    ctx = TraceContext.new()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    parsed = TraceContext.from_traceparent(ctx.to_traceparent())
    assert parsed == ctx
    # W3C header from an external tracer.
    hdr = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
    parsed = TraceContext.from_traceparent(hdr)
    assert parsed is not None
    assert parsed.trace_id == "0af7651916cd43dd8448eb211c80319c"
    assert parsed.span_id == "b7ad6b7169203331"
    for bad in (None, "", "garbage", "00-short-span-01"):
        assert TraceContext.from_traceparent(bad) is None
    # Dict form survives a msgpack/JSON hop.
    assert TraceContext.from_dict(ctx.to_dict()) == ctx
    assert TraceContext.from_dict(None) is None
    assert TraceContext.from_dict({"other": 1}) is None


def test_span_links_under_incoming_context():
    parent = TraceContext.new()
    with Span("child_phase", trace=parent, request_id="link-1") as span:
        pass
    assert span.trace_id == parent.trace_id
    assert span.parent_id == parent.span_id
    assert span.context.trace_id == parent.trace_id
    assert span.context.span_id == span.span_id
    recorded = SPANS.query(request_id="link-1")
    assert recorded and recorded[-1]["parent_id"] == parent.span_id


# -- trace propagation over the real TCP transport ----------------------------


class _TracingEngine(AsyncEngine[Any, Any]):
    """Worker-side engine that records a span under the incoming context."""

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        with Span("engine_side", trace=trace_of(context), request_id=context.id):
            await asyncio.sleep(0)
        yield {"ok": True}


async def test_trace_propagates_frontend_to_engine_over_tcp():
    """Frontend runtime -> worker runtime over real TCP sockets: the worker's
    span must share the root trace_id and link under the rpc_client hop."""
    store = MemoryStore()
    rt_worker = DistributedRuntime(store, TcpTransport(host="127.0.0.1"))
    rt_front = DistributedRuntime(store, TcpTransport(host="127.0.0.1"))
    try:
        await rt_worker.namespace("obs").component("backend").endpoint("generate").serve(
            _TracingEngine()
        )
        client = rt_front.namespace("obs").component("backend").endpoint("generate").client()
        await client.wait_for_instances(count=1, timeout=5)

        rid = "tcp-trace-1"
        root = Span("http_request", request_id=rid)
        ctx = Context(request_id=rid, trace=root.context.to_dict())
        with root:
            items = await collect(client.generate({}, ctx))
        assert items == [{"ok": True}]

        spans = {s["name"]: s for s in SPANS.query(request_id=rid)}
        assert {"http_request", "rpc_client", "engine_side"} <= set(spans)
        # One trace across the wire...
        assert spans["rpc_client"]["trace_id"] == root.trace_id
        assert spans["engine_side"]["trace_id"] == root.trace_id
        # ...with intact parent/child linkage: root -> rpc hop -> engine.
        assert spans["rpc_client"]["parent_id"] == root.span_id
        assert spans["engine_side"]["parent_id"] == spans["rpc_client"]["span_id"]
        assert spans["engine_side"]["status"] == "ok"
    finally:
        await rt_front.close()
        await rt_worker.close()


async def test_untraced_context_stays_untraced_over_tcp():
    """No trace on the context -> no rpc_client span, engine mints a root."""
    store = MemoryStore()
    rt = DistributedRuntime(store, TcpTransport(host="127.0.0.1"))
    try:
        await rt.namespace("obs").component("backend").endpoint("gen2").serve(_TracingEngine())
        client = rt.namespace("obs").component("backend").endpoint("gen2").client()
        await client.wait_for_instances(count=1, timeout=5)
        rid = "tcp-untraced-1"
        await collect(client.generate({}, Context(request_id=rid)))
        spans = {s["name"]: s for s in SPANS.query(request_id=rid)}
        assert "rpc_client" not in spans
        assert spans["engine_side"]["parent_id"] is None
    finally:
        await rt.close()


# -- EngineMetrics registry ---------------------------------------------------


class _FakeCore:
    last_step_info = {"decode_rows": 3, "chunk_rows": 2, "chunk_tokens": 128, "decodable": 3}
    mixed_steps = 7
    stall_violations = 1
    num_preemptions = 2
    admission_rejections = 4
    spec_tokens_proposed = 20
    spec_tokens_accepted = 9
    attn_dispatch_counts = {("decode", "pallas"): 5, ("verify", "fallback"): 1}
    step_gap_ms_last = 0.75
    step_gap_ms_sum = 10.0
    step_gap_ms_count = 8
    overlap_step_counts = {"overlapped": 6, "barrier": 2}
    overlap_barrier_counts = {"spec": 1, "drain": 1}
    constraint_mask_cache_hits = 11
    constraint_mask_cache_misses = 3

    def drain_constraint_build_seconds(self):
        return [0.5, 0.05]

    lost_time_ms = {"gap": 1500.0, "queue": 250.0, "recompile": 40.0}
    step_wall_ms_total = 4000.0
    step_dispatch_ms_total = 3000.0
    step_kind_counts = {"mixed": 5, "decode": 30}
    sentinel = SimpleNamespace(
        active={"recompile_storm": {"value": 9.0, "threshold": 8.0, "since_step": 300}},
        fired={"recompile_storm": 2},
    )
    waiting = ["a"]
    running = ["b", "c"]
    prefilling = ["d"]
    allocator = SimpleNamespace(
        stats=lambda: SimpleNamespace(
            total_pages=64, free_pages=16, cached_pages=8, active_pages=40, hit_rate=0.5
        )
    )
    runner = SimpleNamespace(
        compile_tracker=SimpleNamespace(
            counts=lambda: {("step", "new_shape"): 2, ("multi_step", "warm_cache"): 1}
        )
    )


class _FakeTransfer:
    def stats(self):
        return {
            "blocks": 12, "bytes": 4096, "streams_in_flight": 1,
            "wire_conns": 4, "staged_bytes": 2048,
            "paths": {
                "host_striped": {"transfers": 3, "bytes": 3072},
                "device_pull": {"transfers": 1, "bytes": 1024},
            },
        }


EXPECTED_ENGINE_FAMILIES = {
    "dynamo_engine_step_decode_rows",
    "dynamo_engine_step_chunk_rows",
    "dynamo_engine_step_chunk_tokens",
    "dynamo_engine_attn_dispatch_steps_total",
    "dynamo_engine_step_decodable_seqs",
    "dynamo_engine_mixed_steps_total",
    "dynamo_engine_stall_violations_total",
    "dynamo_engine_preemptions_total",
    "dynamo_engine_admission_rejections_total",
    "dynamo_engine_spec_tokens_proposed_total",
    "dynamo_engine_spec_tokens_accepted_total",
    "dynamo_engine_pages_total",
    "dynamo_engine_pages_free",
    "dynamo_engine_pages_cached",
    "dynamo_engine_pages_active",
    "dynamo_engine_page_utilization_ratio",
    "dynamo_engine_page_fragmentation_ratio",
    "dynamo_engine_prefix_cache_hit_ratio",
    "dynamo_engine_requests_waiting",
    "dynamo_engine_requests_running",
    "dynamo_engine_recompiles_total",
    "dynamo_engine_prefill_queue_depth",
    "dynamo_kv_transfer_blocks_total",
    "dynamo_kv_transfer_bytes_total",
    "dynamo_kv_transfer_streams_in_flight",
    "dynamo_kv_transfer_crc_failures_total",
    "dynamo_kv_transfer_rollbacks_total",
    "dynamo_kv_wire_streams",
    "dynamo_kv_wire_inflight_sessions",
    "dynamo_kv_wire_staged_bytes",
    "dynamo_kv_wire_path_bytes_total",
    "dynamo_kv_wire_path_transfers_total",
    "dynamo_engine_prefill_requeues_total",
    "dynamo_engine_step_gap_ms",
    "dynamo_engine_step_gap_ms_mean",
    "dynamo_engine_overlap_steps_total",
    "dynamo_engine_overlap_barrier_total",
    "dynamo_incidents_captured_total",
    "dynamo_engine_constraint_mask_build_seconds",
    # _created appears once the worker-labeled child exists (the fake core's
    # drain returns samples) — same prometheus_client behavior as the kv
    # phase histogram below.
    "dynamo_engine_constraint_mask_build_seconds_created",
    "dynamo_engine_constraint_mask_cache_hits_total",
    "dynamo_engine_constraint_mask_cache_misses_total",
    "dynamo_engine_admission_queue_depth",
    "dynamo_engine_prefix_onboard_pages_total",
    "dynamo_engine_prefix_onboard_shortfall_pages_total",
    "dynamo_engine_onboard_wait_seconds",
    "dynamo_engine_deadline_misses_total",
    "dynamo_tenant_throttled_total",
    "dynamo_engine_chunk_budget_tokens",
    # Attribution plane (ISSUE 15): time-loss ledger, step-time composition,
    # and the anomaly sentinel's active/fired gauges. True Counters since
    # ISSUE 17 (delta-inc on scrape), so the `_total` sample suffix is
    # honest and each gains a `_created` timestamp family.
    "dynamo_engine_lost_time_seconds_total",
    "dynamo_engine_lost_time_seconds_created",
    "dynamo_engine_step_time_seconds_total",
    "dynamo_engine_step_time_seconds_created",
    "dynamo_engine_step_kind_steps_total",
    "dynamo_engine_step_kind_steps_created",
    "dynamo_anomaly_active",
    "dynamo_anomaly_fired_total",
    # Device-cost plane (ISSUE 19): roofline ledger joins. The counter
    # `_created` families only appear once a cost-carrying core binds, which
    # the fake core here does not.
    "dynamo_engine_roofline_frac",
    "dynamo_engine_hbm_bytes_total",
    "dynamo_engine_flops_total",
    "dynamo_kv_transfer_phase_seconds",
    # prometheus_client emits the histogram's _created timestamps as their
    # own gauge family once a labelled child exists.
    "dynamo_kv_transfer_phase_seconds_created",
}


async def test_engine_metrics_names_labels_and_values():
    async def depth() -> int:
        return 5

    m = (
        EngineMetrics(worker="w1")
        .bind_core(_FakeCore())
        .bind_transfer(_FakeTransfer())
        .bind_queue_depth(depth)
    )
    for phase in KV_PHASES:
        m.observe_phase(phase, 0.01)
    text = (await m.render()).decode()

    # Family-name snapshot: a rename or drop here is an intentional,
    # reviewed change (dashboards and the docs inventory depend on these).
    families = {
        line.split(" ")[2] for line in text.splitlines() if line.startswith("# TYPE ")
    }
    assert families == EXPECTED_ENGINE_FAMILIES

    # Every sample carries the worker label (the federation key).
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert 'worker="w1"' in line, line

    assert 'dynamo_engine_step_decode_rows{worker="w1"} 3.0' in text
    assert 'dynamo_engine_step_chunk_tokens{worker="w1"} 128.0' in text
    assert 'dynamo_engine_mixed_steps_total{worker="w1"} 7.0' in text
    assert 'dynamo_engine_admission_rejections_total{worker="w1"} 4.0' in text
    assert 'dynamo_engine_spec_tokens_proposed_total{worker="w1"} 20.0' in text
    assert 'dynamo_engine_spec_tokens_accepted_total{worker="w1"} 9.0' in text
    assert 'dynamo_engine_step_gap_ms{worker="w1"} 0.75' in text
    assert 'dynamo_engine_step_gap_ms_mean{worker="w1"} 1.25' in text
    assert 'dynamo_engine_overlap_steps_total{mode="overlapped",worker="w1"} 6.0' in text
    assert 'dynamo_engine_overlap_steps_total{mode="barrier",worker="w1"} 2.0' in text
    assert 'dynamo_engine_overlap_barrier_total{reason="spec",worker="w1"} 1.0' in text
    assert 'dynamo_engine_overlap_barrier_total{reason="drain",worker="w1"} 1.0' in text
    assert 'dynamo_engine_constraint_mask_cache_hits_total{worker="w1"} 11.0' in text
    assert 'dynamo_engine_constraint_mask_cache_misses_total{worker="w1"} 3.0' in text
    assert 'dynamo_engine_constraint_mask_build_seconds_count{worker="w1"} 2.0' in text
    assert 'dynamo_engine_constraint_mask_build_seconds_sum{worker="w1"} 0.55' in text
    # Attribution plane: per-cause lost seconds, step-time composition, and
    # the sentinel's active/fired state, all synced from the core.
    assert 'dynamo_engine_lost_time_seconds_total{cause="gap",worker="w1"} 1.5' in text
    assert 'dynamo_engine_lost_time_seconds_total{cause="queue",worker="w1"} 0.25' in text
    assert 'dynamo_engine_lost_time_seconds_total{cause="recompile",worker="w1"} 0.04' in text
    assert 'dynamo_engine_step_time_seconds_total{kind="wall",worker="w1"} 4.0' in text
    assert 'dynamo_engine_step_time_seconds_total{kind="dispatch",worker="w1"} 3.0' in text
    assert 'dynamo_engine_step_time_seconds_total{kind="gap",worker="w1"} 0.01' in text
    assert 'dynamo_engine_step_kind_steps_total{kind="mixed",worker="w1"} 5.0' in text
    assert 'dynamo_engine_step_kind_steps_total{kind="decode",worker="w1"} 30.0' in text
    assert 'dynamo_anomaly_active{kind="recompile_storm",worker="w1"} 1.0' in text
    assert 'dynamo_anomaly_fired_total{kind="recompile_storm",worker="w1"} 2.0' in text
    assert 'dynamo_engine_pages_active{worker="w1"} 40.0' in text
    assert 'dynamo_engine_page_utilization_ratio{worker="w1"} 0.625' in text
    # fragmentation = cached / (free + cached) = 8 / 24
    assert 'dynamo_engine_page_fragmentation_ratio{worker="w1"} 0.3333333333333333' in text
    assert 'dynamo_engine_requests_running{worker="w1"} 3.0' in text
    assert 'dynamo_engine_prefill_queue_depth{worker="w1"} 5.0' in text
    # Recompile counts synced from the runner's CompileTracker.
    assert 'dynamo_engine_recompiles_total{program="step",reason="new_shape",worker="w1"} 2.0' in text
    assert 'dynamo_engine_recompiles_total{program="multi_step",reason="warm_cache",worker="w1"} 1.0' in text
    # Attention dispatch path synced from the core's per-step counts.
    assert 'dynamo_engine_attn_dispatch_steps_total{path="pallas",phase="decode",worker="w1"} 5.0' in text
    assert 'dynamo_engine_attn_dispatch_steps_total{path="fallback",phase="verify",worker="w1"} 1.0' in text
    assert 'dynamo_kv_transfer_blocks_total{worker="w1"} 12.0' in text
    # Wire v3 surface: stripe connections, staging, and per-path attribution.
    assert 'dynamo_kv_wire_streams{worker="w1"} 4.0' in text
    assert 'dynamo_kv_wire_inflight_sessions{worker="w1"} 1.0' in text
    assert 'dynamo_kv_wire_staged_bytes{worker="w1"} 2048.0' in text
    assert 'dynamo_kv_wire_path_bytes_total{path="host_striped",worker="w1"} 3072.0' in text
    assert 'dynamo_kv_wire_path_transfers_total{path="device_pull",worker="w1"} 1.0' in text
    for phase in KV_PHASES:
        assert f'dynamo_kv_transfer_phase_seconds_count{{phase="{phase}",worker="w1"}} 1.0' in text


async def test_unbound_engine_metrics_render_safely():
    text = (await EngineMetrics(worker="idle").render()).decode()
    assert 'dynamo_engine_pages_total{worker="idle"} 0.0' in text


async def test_lost_time_counters_are_monotone_across_scrapes():
    """The lost-time/step-time exports are true Counters (ISSUE 17): a
    scrape incs by the core ledger's delta since the last sync — repeated
    scrapes never double-book, a growing ledger lands exactly once, and a
    rebound core's totals accumulate instead of resetting."""
    core = _FakeCore()
    core.lost_time_ms = {"gap": 1000.0}
    core.step_wall_ms_total = 2000.0
    core.step_kind_counts = {"decode": 10}
    m = EngineMetrics(worker="w1").bind_core(core)

    def sample(text: str, line_start: str) -> float:
        for line in text.splitlines():
            if line.startswith(line_start):
                return float(line.rsplit(" ", 1)[1])
        raise AssertionError(f"{line_start} not found")

    text = (await m.render()).decode()
    assert sample(text, 'dynamo_engine_lost_time_seconds_total{cause="gap",worker="w1"}') == 1.0
    # Idempotent scrape: no growth without ledger growth.
    text = (await m.render()).decode()
    assert sample(text, 'dynamo_engine_lost_time_seconds_total{cause="gap",worker="w1"}') == 1.0
    # Ledger growth lands exactly once.
    core.lost_time_ms = {"gap": 1500.0}
    core.step_kind_counts = {"decode": 12, "mixed": 1}
    text = (await m.render()).decode()
    assert sample(text, 'dynamo_engine_lost_time_seconds_total{cause="gap",worker="w1"}') == 1.5
    assert sample(text, 'dynamo_engine_step_kind_steps_total{kind="decode",worker="w1"}') == 12.0
    assert sample(text, 'dynamo_engine_step_kind_steps_total{kind="mixed",worker="w1"}') == 1.0
    # Rebinding a fresh core (restart) accumulates — monotone across cores.
    fresh = _FakeCore()
    fresh.lost_time_ms = {"gap": 100.0}
    fresh.step_kind_counts = {"decode": 2}
    m.bind_core(fresh)
    text = (await m.render()).decode()
    assert sample(text, 'dynamo_engine_lost_time_seconds_total{cause="gap",worker="w1"}') == 1.6
    assert sample(text, 'dynamo_engine_step_kind_steps_total{kind="decode",worker="w1"}') == 14.0


async def test_federate_text_merges_two_workers():
    parts = [await EngineMetrics(worker="w1").render(), await EngineMetrics(worker="w2").render()]
    merged = federate_text(parts).decode()
    # One header per family...
    assert merged.count("# TYPE dynamo_engine_pages_total gauge") == 1
    assert merged.count("# HELP dynamo_engine_pages_total") == 1
    # ...but both workers' samples survive.
    assert 'dynamo_engine_pages_total{worker="w1"} 0.0' in merged
    assert 'dynamo_engine_pages_total{worker="w2"} 0.0' in merged


def test_metric_names_unique_and_prefixed():
    """Invokes the tools/ hygiene check (ISSUE 3 satellite: CI wiring)."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
    try:
        import check_metric_names
    finally:
        sys.path.pop(0)
    names = check_metric_names.collect_names()
    assert sum(len(v) for v in names.values()) > 20
    assert check_metric_names.check(names) == []
    # The extended hygiene pass: non-empty HELP text and no name registered
    # with conflicting label sets across registries (ISSUE 4 satellite).
    families = check_metric_names.collect_families()
    assert check_metric_names.check_families(families) == []
    assert all(f["help"] for fams in families.values() for f in fams)


def test_env_knobs_documented():
    """Invokes the tools/ env-knob gate (ISSUE 10 satellite: every DYN_*
    knob the source reads appears in a docs env table, and every documented
    knob still exists)."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
    try:
        import check_env_knobs
    finally:
        sys.path.pop(0)
    source, prefixes = check_env_knobs.source_knobs()
    generated = check_env_knobs.generated_knobs()
    documented = check_env_knobs.documented_knobs()
    assert "DYN_OVERLAP" in source and "DYN_WORKER_OVERLAP" in generated
    assert "DYN_CONSTRAINT_LOOKAHEAD_TOKENS" in source
    assert len(source | generated) > 40
    assert check_env_knobs.check(source, generated, prefixes, documented) == []


def test_barrier_reasons_synced():
    """Invokes the tools/ barrier-vocabulary gate (ISSUE 14 satellite): the
    BARRIER_REASONS tuple, the _note_barrier call sites, and the
    SCHEDULER.md barrier table must agree exactly."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
    try:
        import check_barrier_reasons
    finally:
        sys.path.pop(0)
    declared = check_barrier_reasons.declared_reasons()
    recorded = check_barrier_reasons.recorded_reasons()
    documented = check_barrier_reasons.documented_reasons()
    assert "constraint_miss" in declared and "multistep" not in declared
    assert "mm" not in declared
    assert len(documented) == len(declared) > 5
    assert check_barrier_reasons.check(declared, recorded, documented) == []
    # The loss-cause layer (ISSUE 15 satellite): LOSS_CAUSES must be exactly
    # the barrier vocabulary + the literal extras tuple, and the
    # OBSERVABILITY.md loss-cause table must list all of them.
    extras = check_barrier_reasons.source_extra_causes()
    loss = check_barrier_reasons.declared_loss_causes()
    doc_loss = check_barrier_reasons.documented_loss_causes()
    assert extras == ("queue", "admission", "onboard_stall", "preempt", "recompile", "gap")
    assert loss == tuple(declared) + extras
    assert check_barrier_reasons.check_loss_causes(declared, loss, extras, doc_loss) == []


def test_bench_regress_gate(tmp_path, monkeypatch):
    """Invokes the tools/ bench-trajectory gate (ISSUE 15 satellite): the
    newest committed BENCH_r*.json round must hold the trajectory, with
    direction-aware tolerances and the documented waiver knob."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
    try:
        import bench_regress
    finally:
        sys.path.pop(0)
    # Direction table: throughput-like keys gate downward movement,
    # latency-like keys gate upward movement, unknown keys never gate.
    assert bench_regress.direction("decode_tokens_per_sec_per_chip") == 1
    assert bench_regress.direction("loss_coverage_frac") == 1
    assert bench_regress.direction("ttft_ms") == -1
    assert bench_regress.direction("decode_idle_frac") == -1
    assert bench_regress.direction("mystery_key") == 0
    # Tail recovery: a parsed=null wrapper falls back to the last JSON line
    # of the tail; an unusable tail yields no document (round skipped).
    doc = bench_regress._recover_doc(
        {"parsed": None, "tail": 'noise\n{"value": 2.0}\ntrailing'}
    )
    assert doc == {"value": 2.0}
    assert bench_regress._recover_doc({"parsed": None, "tail": "junk"}) is None

    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "parsed": {"value": 100.0, "ttft_ms": 10.0, "odd": 1.0}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "parsed": {"value": 60.0, "ttft_ms": 14.0, "odd": 9.0}}))
    regressions, notes = bench_regress.compare(
        bench_regress.load_rounds(tmp_path), tolerance=0.25)
    assert any(r.startswith("value:") for r in regressions)  # 60 < 100 * 0.75
    assert any(r.startswith("ttft_ms:") for r in regressions)  # 14 > 10 * 1.25
    assert any("odd" in n for n in notes)  # unknown direction stays advisory
    monkeypatch.setenv("DYN_BENCH_REGRESS_WAIVE", "value")
    assert [r.split(":")[0] for r in bench_regress.check(tmp_path)] == ["ttft_ms"]
    monkeypatch.setenv("DYN_BENCH_REGRESS_WAIVE", "all")
    assert bench_regress.check(tmp_path) == []
    # The committed history itself must hold (this is the CI wiring).
    monkeypatch.delenv("DYN_BENCH_REGRESS_WAIVE", raising=False)
    assert bench_regress.check() == []


# -- latency attribution (ISSUE 15 tentpole) ----------------------------------


def _span_doc(name, start_s, dur_ms, *, tid="a" * 32, sid=None, parent=None):
    return {
        "name": name, "trace_id": tid,
        "span_id": sid or (name[:12] + "0000")[:16].ljust(16, "0"),
        "parent_id": parent, "start_ts": start_s, "duration_ms": dur_ms,
        "status": "ok",
    }


def test_build_explain_disagg_budget_sums_to_e2e():
    """The acceptance shape: a disagg request's segments (queue, admission,
    onboard, prefill, KV phases, transfer slack, decode split, recompiles,
    frontend) de-overlap along the span hierarchy and sum to the measured
    E2E latency, residual reported as unattributed."""
    from dynamo_tpu.observability.attribution import build_explain

    t0 = 1000.0
    spans = [
        _span_doc("http_request", t0, 100.0),
        # Remote-prefill window: queue pickup + exec (containing the
        # sender-side KV phases) + scatter, with 4ms of uncovered slack.
        _span_doc("remote_prefill", t0 + 0.005, 30.0),
        _span_doc("prefill_queue_wait", t0 + 0.005, 5.0),
        _span_doc("prefill_exec", t0 + 0.010, 18.0),
        _span_doc("kv_gather", t0 + 0.011, 2.0),
        _span_doc("kv_pack", t0 + 0.013, 1.0),
        _span_doc("kv_wire", t0 + 0.014, 5.0),
        _span_doc("kv_scatter", t0 + 0.028, 3.0),
        # Engine side: queue + admission + onboard waits inside a 12ms TTFT.
        _span_doc("engine_request", t0 + 0.036, 60.0),
        _span_doc("engine_queue_wait", t0 + 0.036, 4.0),
        _span_doc("engine_admission_wait", t0 + 0.040, 2.0),
        _span_doc("engine_onboard_wait", t0 + 0.042, 1.0),
        _span_doc("engine_first_token", t0 + 0.036, 12.0),
    ]
    steps = [
        {"ts": t0 + 0.050 + i * 0.006, "wall_ms": 5.0, "dispatch_ms": 4.0,
         "gap_ms": 1.0, "overlap_mode": "overlapped", "barrier_reason": ""}
        for i in range(8)
    ]
    steps[3]["overlap_mode"] = "barrier"
    steps[3]["barrier_reason"] = "pages"
    step_docs = [
        {"worker": "w-dec", "steps": steps, "compiles": [
            {"ts": t0 + 0.060, "wall_ms": 2.0, "reason": "new_shape", "program": "step"},
            {"ts": t0 + 0.061, "wall_ms": 9.0, "reason": "warm_cache", "program": "step"},
        ]},
        # A second worker with fewer in-window steps must lose the vote:
        # cross-worker records would double-charge the same wall clock.
        {"worker": "w-other", "steps": steps[:2], "compiles": []},
    ]
    doc = build_explain("req-attr-1", spans, step_docs)
    assert doc is not None
    assert doc["decode_worker"] == "w-dec"
    assert doc["steps_in_window"] == 8
    segs = {s["name"]: s["ms"] for s in doc["segments"]}
    assert segs["queue"] == pytest.approx(9.0)  # engine 4 + prefill 5
    assert segs["admission"] == pytest.approx(2.0)
    assert segs["onboard"] == pytest.approx(1.0)
    assert segs["prefill"] == pytest.approx(15.0)  # 10 remote compute + 5 local
    assert segs["kv_gather"] == pytest.approx(2.0)
    assert segs["kv_wire"] == pytest.approx(5.0)
    assert segs["kv_scatter"] == pytest.approx(3.0)
    assert segs["transfer_wait"] == pytest.approx(4.0)  # remote window slack
    assert segs["decode_compute"] == pytest.approx(30.0)  # 32 minus recompile
    assert segs["gap"] == pytest.approx(15.0)
    assert segs["barrier:pages"] == pytest.approx(1.0)
    assert segs["recompile"] == pytest.approx(2.0)  # warm_cache excluded
    assert segs["frontend"] == pytest.approx(10.0)  # e2e - engine - remote
    assert doc["segments"][-1]["name"] == "unattributed"
    assert doc["unattributed_ms"] == pytest.approx(0.0, abs=0.01)
    assert doc["coverage_frac"] == pytest.approx(1.0, abs=0.001)
    assert doc["within_tolerance"] is True
    assert doc["decode_ms"] == pytest.approx(48.0)


def test_build_explain_clamps_decode_overhang_and_handles_edges():
    from dynamo_tpu.observability.attribution import build_explain

    # No http_request/engine_request anchor -> no budget.
    assert build_explain("nope", [_span_doc("kv_wire", 1.0, 3.0)]) is None

    t0 = 2000.0
    spans = [
        _span_doc("engine_request", t0, 20.0),
        _span_doc("engine_first_token", t0, 5.0),
    ]
    # One step whose gap field spans pre-request idle: the raw decode split
    # (45ms) dwarfs the 15ms decode window and must be scaled down to it,
    # not surface as negative unattributed time.
    step_docs = [{"worker": "w1", "steps": [
        {"ts": t0 + 0.010, "wall_ms": 10.0, "dispatch_ms": 9.0, "gap_ms": 35.0},
    ], "compiles": []}]
    doc = build_explain("req-clamp", spans, step_docs)
    segs = {s["name"]: s["ms"] for s in doc["segments"]}
    assert segs.get("decode_compute", 0.0) + segs.get("gap", 0.0) == pytest.approx(15.0, abs=0.01)
    assert "frontend" not in segs  # anchor IS the engine span
    assert doc["within_tolerance"] is True

    # TTFT == engine duration: a zero decode window zeroes the decode split.
    spans2 = [
        _span_doc("engine_request", t0, 10.0),
        _span_doc("engine_first_token", t0, 10.0),
    ]
    doc2 = build_explain("req-zero-decode", spans2, step_docs)
    segs2 = {s["name"]: s["ms"] for s in doc2["segments"]}
    assert "decode_compute" not in segs2 and "gap" not in segs2
    assert segs2["prefill"] == pytest.approx(10.0)
    assert doc2["within_tolerance"] is True


def test_loss_cause_vocabulary_pinned_to_barriers():
    from dynamo_tpu.engine.core import BARRIER_REASONS
    from dynamo_tpu.observability import EXTRA_LOSS_CAUSES, LOSS_CAUSES  # lazy export

    assert LOSS_CAUSES[: len(BARRIER_REASONS)] == tuple(BARRIER_REASONS)
    assert LOSS_CAUSES[len(BARRIER_REASONS):] == EXTRA_LOSS_CAUSES
    assert len(set(LOSS_CAUSES)) == len(LOSS_CAUSES)
    assert {"queue", "admission", "onboard_stall", "preempt", "recompile", "gap"} <= set(LOSS_CAUSES)


def test_engine_lost_time_covers_noncompute_wall():
    """The fleet-wide ledger (acceptance criterion): after serving traffic,
    the per-cause lost-time totals explain >= 90% of the engine's
    non-compute wall time (wall + gap - dispatch), every cause in the
    pinned vocabulary."""
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.mocker import MockRunner
    from dynamo_tpu.observability.attribution import LOSS_CAUSES
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    runner = MockRunner(num_pages=64, page_size=16, realtime=False)
    core = EngineCore(runner, EngineConfig(
        num_pages=64, page_size=16, max_batch_size=4, max_seq_len=256,
        chunk_prefill_tokens=32, enable_prefix_caching=False,
    ))
    for _ in range(3):
        core.add_request(PreprocessedRequest(
            token_ids=list(range(1, 25)),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=12, ignore_eos=True),
        ))
    steps = 0
    while core.has_work and steps < 200:
        core.step()
        steps += 1
    assert not core.has_work

    assert set(core.lost_time_ms) <= set(LOSS_CAUSES)
    noncompute = core.step_wall_ms_total + core.step_gap_ms_sum - core.step_dispatch_ms_total
    step_lost = sum(
        ms for cause, ms in core.lost_time_ms.items()
        if cause not in ("queue", "admission")
    )
    if noncompute > 0.0:
        assert step_lost >= 0.9 * noncompute
    # The sentinel rode the same step stream without firing on quiet load.
    assert core.sentinel is not None
    assert core.sentinel.fired == {}


# -- anomaly sentinel ---------------------------------------------------------


def _feed(sent, *, n=1, recompiles=0, shortfall=0, barrier=False, gap=1.0):
    for _ in range(n):
        sent.observe_step(
            wall_ms=5.0, gap_ms=gap, barrier=barrier, outputs=3, decode_rows=3,
            recompiles=recompiles, shortfall_pages=shortfall,
        )


def test_anomaly_sentinel_quiet_stream_never_fires():
    from dynamo_tpu.config import AnomalySettings
    from dynamo_tpu.observability.anomaly import AnomalySentinel

    sent = AnomalySentinel(AnomalySettings(window=16, min_samples=32))
    _feed(sent, n=400)
    assert sent.active == {} and sent.fired == {}


def test_anomaly_sentinel_recompile_storm_fires_once_then_clears():
    from dynamo_tpu.config import AnomalySettings
    from dynamo_tpu.observability.anomaly import AnomalySentinel
    from dynamo_tpu.observability.flight import ANOMALY

    records = []
    flight = SimpleNamespace(record=lambda kind, **f: records.append((kind, f)))
    sent = AnomalySentinel(
        AnomalySettings(window=16, min_samples=32, clear_after=8), flight=flight
    )
    _feed(sent, n=64)
    # A storm: the cumulative compile counter jumps inside one window.
    for i in range(16):
        _feed(sent, recompiles=i)
    assert "recompile_storm" in sent.active
    assert sent.fired.get("recompile_storm") == 1  # one rising edge, no flap
    storm_records = [f for kind, f in records if kind == ANOMALY]
    assert [f["anomaly"] for f in storm_records] == ["recompile_storm"]
    assert storm_records[0]["value"] >= storm_records[0]["threshold"]
    # Hysteresis: clear_after consecutive quiet steps retire the alert but
    # the fired counter keeps the history.
    _feed(sent, n=24, recompiles=15)
    assert "recompile_storm" not in sent.active
    assert sent.fired.get("recompile_storm") == 1


def test_anomaly_sentinel_barrier_frac_spike_fires():
    from dynamo_tpu.config import AnomalySettings
    from dynamo_tpu.observability.anomaly import AnomalySentinel

    sent = AnomalySentinel(AnomalySettings(window=16, min_samples=32))
    _feed(sent, n=64)  # quiet baseline arms the relative detectors
    _feed(sent, n=16, barrier=True)
    assert "barrier_frac_spike" in sent.active
    assert sent.active["barrier_frac_spike"]["value"] >= 0.5
    assert sent.fired["barrier_frac_spike"] == 1
    # The spike also shows up as gap-free barrier steps, never as a goodput
    # drop (outputs stayed constant).
    assert "goodput_drop" not in sent.fired


def test_anomaly_kinds_exported():
    from dynamo_tpu.observability import ANOMALY_KINDS

    assert set(ANOMALY_KINDS) == {
        "barrier_frac_spike", "step_gap_regression", "goodput_drop",
        "recompile_storm", "onboard_shortfall_burst",
    }


# -- timeline assembly --------------------------------------------------------


def test_assemble_timeline_orders_and_links():
    t0 = 1000.0
    tid = "t" * 32
    spans = [
        {"name": "kv_wire", "trace_id": tid, "span_id": "c" * 16, "parent_id": "b" * 16,
         "start_ts": t0 + 0.020, "duration_ms": 5.0, "status": "ok"},
        {"name": "http_request", "trace_id": tid, "span_id": "a" * 16, "parent_id": None,
         "start_ts": t0, "duration_ms": 50.0, "status": "ok"},
        {"name": "remote_prefill", "trace_id": tid, "span_id": "b" * 16, "parent_id": "a" * 16,
         "start_ts": t0 + 0.010, "duration_ms": 30.0, "status": "ok"},
    ]
    doc = assemble_timeline("req-1", spans)
    assert doc["trace_ids"] == [tid]
    assert [s["name"] for s in doc["spans"]] == ["http_request", "remote_prefill", "kv_wire"]
    assert [s["offset_ms"] for s in doc["spans"]] == [0.0, 10.0, 20.0]
    root = doc["spans"][0]
    assert root["root"] is True and root["children"] == [1]
    assert doc["spans"][1]["children"] == [2]
    assert doc["duration_ms"] == 50.0
    assert all("parent_evicted" not in s for s in doc["spans"])


def test_assemble_timeline_surfaces_orphans_of_evicted_parents():
    """Regression (ISSUE 15 satellite): a span whose parent fell out of the
    bounded ring used to hang the tree — it must surface at top level,
    flagged parent_evicted, with its own children intact."""
    t0 = 3000.0
    tid = "d" * 32
    spans = [
        {"name": "engine_request", "trace_id": tid, "span_id": "a" * 16,
         "parent_id": "gone000000000000", "start_ts": t0, "duration_ms": 9.0,
         "status": "ok"},
        {"name": "kv_scatter", "trace_id": tid, "span_id": "b" * 16,
         "parent_id": "a" * 16, "start_ts": t0 + 0.001, "duration_ms": 2.0,
         "status": "ok"},
    ]
    doc = assemble_timeline("req-orphan", spans)
    orphan = doc["spans"][0]
    assert orphan["name"] == "engine_request"
    assert orphan["root"] is True and orphan["parent_evicted"] is True
    assert orphan["children"] == [1]
    assert "parent_evicted" not in doc["spans"][1]


def test_span_buffer_eviction_keeps_children_visible(monkeypatch):
    """An undersized ring (DYN_SPAN_BUFFER) evicting the root must not make
    its surviving children vanish from the assembled timeline."""
    import dynamo_tpu.tracing as tracing

    monkeypatch.setenv("DYN_SPAN_BUFFER", "2")
    buf = tracing.SpanBuffer(tracing._buffer_capacity())
    assert buf._spans.maxlen == 2
    monkeypatch.setattr(tracing, "SPANS", buf)
    rid = "evict-regress-1"
    root = Span("http_request", request_id=rid)
    with root:
        pass
    with Span("engine_request", trace=root.context, request_id=rid) as eng:
        pass
    with Span("engine_first_token", trace=eng.context, request_id=rid):
        pass
    spans = buf.query(request_id=rid)
    assert {s["name"] for s in spans} == {"engine_request", "engine_first_token"}
    doc = assemble_timeline(rid, spans)
    by_name = {s["name"]: s for s in doc["spans"]}
    assert by_name["engine_request"]["root"] is True
    assert by_name["engine_request"]["parent_evicted"] is True
    assert by_name["engine_first_token"].get("parent_evicted") is None
    assert doc["span_count"] == 2


async def test_debug_traces_endpoint_assembles_mocked_disagg_hop():
    """GET /debug/traces/{id}: frontend-local spans + a mocked remote
    prefill worker's spans merge into one timeline under one trace_id."""
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.frontend.metrics import FrontendMetrics
    from dynamo_tpu.frontend.model_manager import ModelManager

    rid = "mock-disagg-1"
    root = Span("http_request", request_id=rid, model="m", endpoint="completions")
    with root:
        with Span("router_decision", trace=root.context, request_id=rid):
            pass

    # The "remote process": span docs as a prefill worker's SpanQueryService
    # would return them (same trace_id, linked under the frontend root).
    now = time.time()
    remote = [
        {"name": "prefill_exec", "trace_id": root.trace_id, "span_id": "e" * 16,
         "parent_id": root.span_id, "request_id": rid, "start_ts": now + 0.01,
         "duration_ms": 20.0, "status": "ok", "host": "prefill-host"},
        {"name": "kv_wire", "trace_id": root.trace_id, "span_id": "f" * 16,
         "parent_id": "e" * 16, "request_id": rid, "start_ts": now + 0.02,
         "duration_ms": 4.0, "status": "ok", "host": "prefill-host"},
    ]

    class FakeTelemetry:
        async def collect_spans(self, *, request_id=None, trace_id=None):
            if request_id is not None:
                return [dict(s) for s in remote if s["request_id"] == request_id]
            return [dict(s) for s in remote if s["trace_id"] == trace_id]

        async def collect_metrics_texts(self):
            return []

    service = HttpService(ModelManager(), metrics=FrontendMetrics(), telemetry=FakeTelemetry())
    port = await service.start("127.0.0.1", 0)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/debug/traces/{rid}") as r:
                assert r.status == 200
                doc = await r.json()
            async with s.get(f"http://127.0.0.1:{port}/debug/traces/no-such-request") as r:
                assert r.status == 404
    finally:
        await service.stop()

    assert doc["request_id"] == rid
    assert doc["trace_ids"] == [root.trace_id]  # one trace across both processes
    names = [s["name"] for s in doc["spans"]]
    assert set(names) >= {"http_request", "router_decision", "prefill_exec", "kv_wire"}
    assert doc["span_count"] == len(names) == len({s["span_id"] for s in doc["spans"]})
    hosts = {s.get("host") for s in doc["spans"]}
    assert "prefill-host" in hosts
    by_name = {s["name"]: s for s in doc["spans"]}
    assert by_name["http_request"]["root"] is True
    assert names.index("prefill_exec") < names.index("kv_wire")


async def test_debug_explain_endpoint_serves_budget():
    """GET /debug/explain/{id}: the frontend joins the span union with the
    debug_explain fan-out's windowed STEP records into a segment budget."""
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.frontend.metrics import FrontendMetrics
    from dynamo_tpu.frontend.model_manager import ModelManager

    rid = "mock-explain-1"
    root = Span("http_request", request_id=rid, model="m", endpoint="completions")
    with root:
        time.sleep(0.05)
    start = SPANS.query(request_id=rid)[-1]["start_ts"]

    class FakeTelemetry:
        def __init__(self):
            self.windows = []

        async def collect_spans(self, *, request_id=None, trace_id=None):
            return []

        async def collect_metrics_texts(self):
            return []

        async def collect_explain(self, *, t0=None, t1=None):
            self.windows.append((t0, t1))
            # One step overhanging the ~50ms window: the clamp scales the
            # decode split down to it, so the budget closes exactly.
            return [{"worker": "w-x", "steps": [
                {"ts": start + 0.010, "wall_ms": 60.0, "dispatch_ms": 55.0,
                 "gap_ms": 0.0, "overlap_mode": "overlapped", "barrier_reason": ""},
            ], "compiles": [], "lost_time_ms": {}}]

    telemetry = FakeTelemetry()
    service = HttpService(ModelManager(), metrics=FrontendMetrics(), telemetry=telemetry)
    port = await service.start("127.0.0.1", 0)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/debug/explain/{rid}") as r:
                assert r.status == 200
                doc = await r.json()
            async with s.get(f"http://127.0.0.1:{port}/debug/explain/no-such") as r:
                assert r.status == 404
    finally:
        await service.stop()

    assert doc["request_id"] == rid
    assert doc["decode_worker"] == "w-x"
    assert doc["steps_in_window"] == 1
    assert doc["segments"][-1]["name"] == "unattributed"
    assert doc["within_tolerance"] is True
    assert doc["coverage_frac"] == pytest.approx(1.0, abs=0.01)
    # The fan-out was windowed to the request's span bounds (padded 1s).
    (t0, t1), = telemetry.windows
    assert t0 <= start and t1 >= start + 0.05


# -- full-stack disagg timeline + federation (acceptance criterion) -----------


@pytest.mark.e2e
async def test_disagg_request_yields_single_trace_timeline(monkeypatch):
    """A disaggregated request (remote prefill via the wire path + local
    decode) produces one /debug/traces timeline: spans from the decode side
    and the prefill worker under a single trace_id, including the
    KV-transfer phase spans; /metrics federates the engine registries."""
    from dynamo_tpu.disagg import device_transfer, prefill_worker
    from dynamo_tpu.disagg.router import DisaggConfig
    from dynamo_tpu.launch import run_local
    from dynamo_tpu.observability.attribution import LOSS_CAUSES

    # Force the chunked TCP wire path (the phase-span source): disable the
    # same-process device shortcut and the cross-process device pull.
    monkeypatch.setattr(device_transfer.REGISTRY, "lookup", lambda addr: None)

    async def no_pull(*a, **kw):
        raise RuntimeError("pull disabled for wire-path test")

    monkeypatch.setattr(prefill_worker, "send_pull_offer", no_pull)

    disagg = DisaggConfig(max_local_prefill_length=24, min_remote_prefill_blocks=1)
    handles = await run_local(
        "test-tiny", port=0, num_workers=1, num_prefill_workers=1,
        disagg=disagg, num_pages=64, max_batch_size=8,
    )
    base = f"http://127.0.0.1:{handles['port']}"
    rid = "disagg-trace-e2e-1"
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "test-tiny", "prompt": "r" * 48, "max_tokens": 4,
                "temperature": 0, "request_id": rid,
            }
            traceparent = TraceContext.new().to_traceparent()
            async with s.post(
                base + "/v1/completions", json=body, headers={"traceparent": traceparent}
            ) as r:
                assert r.status == 200, await r.text()
                # Satellite: the unary response surfaces the trace id, so
                # /debug/traces and /debug/explain are reachable without
                # grepping logs — and it is the ingested traceparent's id.
                assert r.headers["x-dynamo-trace-id"] == traceparent.split("-")[1]

            # The prefill worker's final phase spans land just after the
            # decode response unblocks — poll the timeline briefly.
            needed = {"http_request", "remote_prefill", "prefill_exec", "kv_wire", "kv_scatter"}
            doc = None
            for _ in range(100):
                async with s.get(f"{base}/debug/traces/{rid}") as r:
                    if r.status == 200:
                        doc = await r.json()
                        if needed <= {sp["name"] for sp in doc["spans"]}:
                            break
                await asyncio.sleep(0.05)
            assert doc is not None, "no timeline assembled"
            names = {sp["name"] for sp in doc["spans"]}
            assert needed <= names, names
            # Every hop under ONE trace, rooted at the ingested traceparent.
            assert doc["trace_ids"] == [traceparent.split("-")[1]]
            assert "engine_queue_wait" in names  # decode-side admission span
            statuses = {sp["status"] for sp in doc["spans"]}
            assert statuses == {"ok"}

            # Attribution (ISSUE 15 acceptance): the explain budget's
            # segments must sum to within tolerance of the measured E2E,
            # joined from this worker's live flight STEP records.
            explain = None
            for _ in range(100):
                async with s.get(f"{base}/debug/explain/{rid}") as r:
                    if r.status == 200:
                        explain = await r.json()
                        if explain.get("within_tolerance") and explain.get("steps_in_window", 0) > 0:
                            break
                await asyncio.sleep(0.05)
            assert explain is not None, "no explain budget assembled"
            assert explain["within_tolerance"] is True, explain
            assert explain["steps_in_window"] > 0
            seg_names = [sg["name"] for sg in explain["segments"]]
            assert seg_names[-1] == "unattributed"  # residual always reported
            assert abs(explain["unattributed_ms"]) <= 0.1 * explain["e2e_ms"]
            assert explain["trace_id"] == traceparent.split("-")[1]
            known = set(LOSS_CAUSES) | {
                "queue", "admission", "onboard", "prefill", "transfer_wait",
                "decode_compute", "recompile", "frontend", "unattributed",
                "kv_gather", "kv_pack", "kv_wire", "kv_scatter",
            }
            for name in seg_names:
                base_name = name.split(":", 1)[1] if name.startswith("barrier:") else name
                assert base_name in known, name

            # Flight recorder (ISSUE 4): force a mixed step — hold one
            # stream in decode while a second short prompt (below the local
            # prefill threshold) is admitted, so its chunk rows fuse with
            # the live decode rows in one dispatch.
            async with s.post(
                base + "/v1/completions",
                json={"model": "test-tiny", "prompt": "s" * 8, "max_tokens": 48,
                      "temperature": 0, "stream": True},
            ) as r1:
                assert r1.status == 200
                # The SSE response carries the trace id too (satellite).
                assert len(r1.headers["x-dynamo-trace-id"]) == 32
                await r1.content.readany()  # first chunk: decode is live
                async with s.post(
                    base + "/v1/completions",
                    json={"model": "test-tiny", "prompt": "t" * 12, "max_tokens": 4,
                          "temperature": 0},
                ) as r2:
                    assert r2.status == 200, await r2.text()
                async for _ in r1.content:  # drain the stream to completion
                    pass

            flight_doc = None
            records: list[dict] = []
            for _ in range(100):
                async with s.get(base + "/debug/flight/all") as r:
                    if r.status == 200:
                        flight_doc = await r.json()
                        records = [
                            rec
                            for w in flight_doc["workers"].values()
                            for rec in w["records"]
                        ]
                        if any(rec["kind"] == "compile" for rec in records) and any(
                            rec.get("step_kind") == "mixed" for rec in records
                        ):
                            break
                await asyncio.sleep(0.05)
            assert flight_doc is not None, "no flight rings collected"
            kinds = {rec["kind"] for rec in records}
            assert "step" in kinds and "compile" in kinds, kinds
            assert any(rec.get("step_kind") == "mixed" for rec in records), (
                sorted({rec.get("step_kind") for rec in records if rec["kind"] == "step"})
            )
            # Records are ordered (monotonic seq) within each worker's ring,
            # and step records carry the per-step composition fields.
            for w in flight_doc["workers"].values():
                seqs = [rec["seq"] for rec in w["records"]]
                assert seqs == sorted(seqs)
            step_rec = next(rec for rec in records if rec["kind"] == "step")
            for key in ("decode_rows", "chunk_tokens", "free_pages", "wall_ms", "preemptions"):
                assert key in step_rec, step_rec
            compile_rec = next(rec for rec in records if rec["kind"] == "compile")
            assert compile_rec["program"] and compile_rec["reason"] in ("new_shape", "warm_cache")
            # Single-worker addressing: {worker} narrows the fan-out.
            one = next(iter(flight_doc["workers"]))
            async with s.get(f"{base}/debug/flight/{one}?last=5&kind=step") as r:
                assert r.status == 200
                narrowed = await r.json()
            assert set(narrowed["workers"]) == {one}
            assert len(narrowed["workers"][one]["records"]) <= 5
            assert all(
                rec["kind"] == "step" for rec in narrowed["workers"][one]["records"]
            )

            # Federation: the frontend /metrics render includes both engine
            # registries' families with per-worker labels, plus the
            # SLO-conditioned goodput accounting (ISSUE 4).
            async with s.get(base + "/metrics") as r:
                text = await r.text()
            assert "dynamo_frontend_requests_total" in text
            assert "dynamo_engine_step_decode_rows" in text
            assert "dynamo_engine_prefill_queue_depth" in text
            assert "dynamo_goodput_tokens_total" in text
            assert "dynamo_output_tokens_total" in text
            assert "dynamo_engine_recompiles_total" in text
            assert "dynamo_frontend_ttft_quantile_seconds" in text
            # The time-loss ledger federates with per-cause labels drawn
            # from the pinned vocabulary (ISSUE 15).
            assert "dynamo_engine_lost_time_seconds_total" in text
            assert 'dynamo_engine_step_time_seconds_total' in text
            causes = {
                line.split('cause="', 1)[1].split('"', 1)[0]
                for line in text.splitlines()
                if line.startswith("dynamo_engine_lost_time_seconds_total{")
            }
            assert causes and causes <= set(LOSS_CAUSES), causes
            assert 'dynamo_kv_transfer_phase_seconds_count{phase="wire"' in text
            assert text.count("# TYPE dynamo_engine_pages_total gauge") == 1
            workers = {
                line.split('worker="', 1)[1].split('"', 1)[0]
                for line in text.splitlines()
                if line.startswith("dynamo_engine_pages_total{")
            }
            assert len(workers) == 2, workers  # decode + prefill registries
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
