"""Incident plane: capture-on-anomaly black-box bundles, SLO burn-rate
alerting, the fleet-wide /debug/incidents surface, and the control tower.

Covers the ISSUE 18 acceptance criteria that are unit-testable without a
fleet: store size-cap eviction, exactly-one-bundle-per-rising-edge (no
hysteresis duplicates), deterministic burn-window trip + clear on synthetic
attainment streams, frontend fetch of worker bundles, and a `top --once`
render against a live mock frontend. The live fleetsim chaos scenario is
``tests/test_fleetsim.py::test_scenario_incident_capture_live``.
"""

import pathlib
import sys
import time
from types import SimpleNamespace

import aiohttp
import pytest

from dynamo_tpu.config import AlertSettings, AnomalySettings, IncidentSettings, SloSettings
from dynamo_tpu.mocker import build_mock_core
from dynamo_tpu.observability.anomaly import AnomalySentinel
from dynamo_tpu.observability.flight import CRASH
from dynamo_tpu.observability.incidents import (
    INCIDENT_KINDS,
    IncidentCapture,
    IncidentStore,
)
from dynamo_tpu.observability.slo import ALERT_KINDS, SloAccountant
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.tracing import Span

# -- store -------------------------------------------------------------------


def _bundle(kind="anomaly", **extra):
    return {"ts": time.time(), "kind": kind, "worker": "w-test",
            "trigger": {"anomaly": "goodput_drop"}, "flight": [], "spans": [],
            "loss": None, **extra}


def test_incident_store_save_list_get(tmp_path):
    store = IncidentStore(str(tmp_path / "inc"))
    ids = [store.save(_bundle()) for _ in range(3)]
    assert len(set(ids)) == 3
    assert ids == sorted(ids)  # ids are chronological by construction
    assert len(store) == 3

    summaries = store.list()
    assert [s["id"] for s in summaries] == ids  # oldest first
    for s in summaries:
        assert s["kind"] == "anomaly"
        assert s["worker"] == "w-test"
        assert s["trigger"] == {"anomaly": "goodput_drop"}
        assert s["bytes"] > 0

    full = store.get(ids[0])
    assert full["id"] == ids[0]
    assert full["kind"] == "anomaly"
    # Unknown ids and traversal attempts come back None, never raise.
    assert store.get("inc-nope") is None
    assert store.get("../etc/passwd") is None
    assert store.get(".hidden") is None


def test_incident_store_count_cap_evicts_oldest(tmp_path):
    store = IncidentStore(str(tmp_path / "inc"), max_bundles=3)
    ids = [store.save(_bundle()) for _ in range(5)]
    assert len(store) == 3
    kept = [s["id"] for s in store.list()]
    assert kept == ids[2:]  # the two oldest were evicted
    assert store.get(ids[0]) is None
    assert store.get(ids[-1]) is not None


def test_incident_store_byte_cap_evicts_oldest(tmp_path):
    store = IncidentStore(str(tmp_path / "inc"), max_bundles=100, max_bytes=2000)
    big = _bundle(flight=[{"pad": "x" * 64} for _ in range(10)])  # ~800 B each
    ids = [store.save(dict(big)) for _ in range(6)]
    remaining = store.list()
    assert 0 < len(remaining) < 6
    total = sum(s["bytes"] for s in remaining)
    assert total <= 2000
    assert [s["id"] for s in remaining] == ids[-len(remaining):]


# -- capture -----------------------------------------------------------------


def _capture(tmp_path, **kw):
    settings = IncidentSettings(
        dir=str(tmp_path / "inc"), cooldown_s=kw.pop("cooldown_s", 0.0),
        span_window_s=kw.pop("span_window_s", 30.0), **kw
    )
    return IncidentCapture(settings, worker="w-test")


def test_capture_bundle_contents(tmp_path):
    flight = SimpleNamespace(snapshot=lambda last=None, kind=None: [
        {"kind": "step", "seq": 1}, {"kind": "anomaly", "anomaly": "goodput_drop"},
    ])
    core = SimpleNamespace(loss_snapshot=lambda: {"lost_time_ms": {"barrier": 3.0}})
    cap = IncidentCapture(
        IncidentSettings(dir=str(tmp_path / "inc"), span_window_s=30.0),
        worker="w-test", core=core, flight=flight,
    )
    with Span("engine_step", request_id="req-inc-1"):
        pass
    incident_id = cap.capture(
        "anomaly", {"anomaly": "goodput_drop", "value": 0.1, "threshold": 0.5}
    )
    assert incident_id is not None
    assert cap.captured == {"anomaly": 1}

    bundle = cap.store.get(incident_id)
    assert bundle["kind"] == "anomaly"
    assert bundle["worker"] == "w-test"
    assert bundle["trigger"]["anomaly"] == "goodput_drop"
    # The black box: flight excerpt, intersecting spans, loss snapshot.
    assert {r["kind"] for r in bundle["flight"]} == {"step", "anomaly"}
    assert any(s.get("name") == "engine_step" for s in bundle["spans"])
    assert bundle["loss"] == {"lost_time_ms": {"barrier": 3.0}}
    # Config + device-trace context ride along for the postmortem join.
    assert "incident" in bundle["config"] and "env" in bundle["config"]
    # capture_available/artifact_dir landed with the device-cost plane
    # (ISSUE 19): the bundle tells the responder whether a follow-up
    # /debug/profile capture is possible and where artifacts will land.
    assert set(bundle["device_trace"]) == {
        "armed", "dir", "capture_available", "artifact_dir",
    }
    assert isinstance(bundle["device_trace"]["capture_available"], bool)


def test_capture_cooldown_and_disable(tmp_path):
    cap = _capture(tmp_path, cooldown_s=60.0)
    trigger = {"anomaly": "recompile_storm"}
    assert cap.capture("anomaly", trigger) is not None
    # Same kind within the cooldown: suppressed (a flapping detector must
    # not flood the store).
    assert cap.capture("anomaly", trigger) is None
    # A different anomaly kind has its own cooldown key.
    assert cap.capture("anomaly", {"anomaly": "goodput_drop"}) is not None
    assert cap.captured == {"anomaly": 2}

    off = IncidentCapture(
        IncidentSettings(enable=False, dir=str(tmp_path / "off")), worker="w")
    assert off.capture("crash", {"error": "X"}) is None
    assert len(off.store) == 0


def test_capture_never_raises(tmp_path):
    cap = _capture(tmp_path)
    cap.store.save = lambda bundle: (_ for _ in ()).throw(OSError("disk gone"))
    assert cap.capture("crash", {"error": "X"}) is None  # swallowed, logged


# -- anomaly -> incident e2e -------------------------------------------------


def test_anomaly_rising_edge_captures_exactly_one_bundle(tmp_path):
    """One bundle per rising edge: the sentinel's hysteresis keeps the
    detector active for many steps but only the edge captures; after a
    clear, the next edge captures again."""
    cap = _capture(tmp_path, cooldown_s=0.0)
    sent = AnomalySentinel(
        AnomalySettings(window=16, min_samples=32, clear_after=8),
        on_fire=lambda kind, info: cap.capture("anomaly", info),
    )

    def feed(n, recompiles=0):
        for _ in range(n):
            sent.observe_step(wall_ms=5.0, gap_ms=1.0, barrier=False, outputs=3,
                              decode_rows=3, recompiles=recompiles,
                              shortfall_pages=0)

    feed(64)  # quiet baseline
    for i in range(16):
        feed(1, recompiles=i)  # a storm inside one window
    assert sent.fired.get("recompile_storm") == 1
    assert len(cap.store) == 1  # the edge captured; active steps did not

    feed(24, recompiles=15)  # hysteresis clears the alert
    assert "recompile_storm" not in sent.active
    assert len(cap.store) == 1  # clearing is not a capture

    for i in range(16):
        feed(1, recompiles=16 + i)  # a second storm: a new rising edge
    assert sent.fired.get("recompile_storm") == 2
    assert len(cap.store) == 2

    bundle = cap.store.get(cap.store.list()[-1]["id"])
    assert bundle["kind"] == "anomaly"
    assert bundle["trigger"]["anomaly"] == "recompile_storm"
    assert bundle["trigger"]["value"] >= bundle["trigger"]["threshold"]


def test_engine_core_crash_captures_bundle(tmp_path, monkeypatch):
    """A step crash leaves a self-contained postmortem: the bundle's flight
    excerpt ends with the CRASH record and the trigger names the exception."""
    monkeypatch.setenv("DYN_INCIDENT_DIR", str(tmp_path / "inc"))
    core = build_mock_core(realtime=False)
    core.add_request(PreprocessedRequest(
        token_ids=[1, 2, 3], sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=4),
    ))
    core.step()  # healthy context before the crash

    def boom():
        raise RuntimeError("device array poisoned")

    monkeypatch.setattr(core, "_step_locked", boom)
    with pytest.raises(RuntimeError, match="device array poisoned"):
        core.step()

    assert core.incidents.captured == {"crash": 1}
    summaries = core.incidents.store.list()
    assert len(summaries) == 1
    bundle = core.incidents.store.get(summaries[0]["id"])
    assert bundle["kind"] == "crash"
    assert bundle["trigger"]["error"] == "RuntimeError"
    assert "device array poisoned" in bundle["trigger"]["detail"]
    kinds = [r["kind"] for r in bundle["flight"]]
    assert kinds[-1] == CRASH  # the excerpt references the crash itself
    assert bundle["loss"] is not None  # loss_snapshot rode along


# -- burn-rate alerting ------------------------------------------------------


def _alert_acct(**kw):
    fired = []
    defaults = dict(objective=0.9, fast_window=8, slow_window=64,
                    fast_burn=4.0, slow_burn=2.0, min_requests=8,
                    clear_after=8)
    acct = SloAccountant(
        SloSettings(ttft_ms=100.0, itl_p99_ms=20.0),
        AlertSettings(**{**defaults, **kw}),
        on_fire=lambda kind, info: fired.append((kind, info)),
    )
    return acct, fired


def _good(acct, n):
    for _ in range(n):
        acct.account(ttft_s=0.01, itl_gaps=[0.001], output_tokens=4, ok=True)


def _bad(acct, n):
    for _ in range(n):
        acct.account(ttft_s=1.0, itl_gaps=[0.001], output_tokens=4, ok=True)


def test_burn_rate_math_on_synthetic_stream():
    acct, _ = _alert_acct()
    _good(acct, 8)
    assert acct.burn_rates() == {"fast": 0.0, "slow": 0.0}
    _bad(acct, 4)  # fast window now 4 misses / 8 requests
    # budget = 1 - 0.9 = 0.1; miss_frac(fast) = 0.5 -> burn 5x.
    assert acct.burn_rates()["fast"] == pytest.approx(5.0)
    assert acct.burn_rates()["slow"] == pytest.approx(4.0 / 12 / 0.1, abs=0.01)


def test_burn_alert_trips_fast_window_and_clears_with_hysteresis():
    # slow_burn un-trippable: this test isolates the fast window's edges.
    acct, fired = _alert_acct(slow_burn=1000.0)
    _good(acct, 8)
    assert acct.alerts_active == {} and fired == []

    _bad(acct, 4)  # burn hits 5x >= the 4x fast threshold
    assert "slo_fast_burn" in acct.alerts_active
    assert acct.alerts_active["slo_fast_burn"]["window"] == "fast"
    assert acct.alerts_fired == {"slo_fast_burn": 1}
    # The rising edge fired the sink exactly once, with the window state.
    assert [k for k, _ in fired] == ["slo_fast_burn"]
    assert fired[0][1]["alert"] == "slo_fast_burn"
    assert fired[0][1]["value"] >= fired[0][1]["threshold"]

    _bad(acct, 8)  # still burning: active, no duplicate edge
    assert acct.alerts_fired == {"slo_fast_burn": 1}
    assert len(fired) == 1

    # Recovery: burn falls under threshold once the window holds < 4 misses
    # (4 met requests in), then clear_after=8 further quiet requests retire
    # the alert — 12 met requests total, deterministically.
    _good(acct, 11)
    assert "slo_fast_burn" in acct.alerts_active  # hysteresis still holding
    _good(acct, 1)
    assert "slo_fast_burn" not in acct.alerts_active
    assert acct.alerts_fired == {"slo_fast_burn": 1}  # history survives

    # A fresh violation is a new edge.
    _bad(acct, 4)
    assert acct.alerts_fired == {"slo_fast_burn": 2}


def test_slow_burn_alert_needs_sustained_violation():
    acct, fired = _alert_acct()
    _good(acct, 48)
    _bad(acct, 4)
    # Fast trips on the sharp spike; slow (4/52 misses -> 0.77x) does not.
    assert "slo_fast_burn" in acct.alerts_active
    assert "slo_slow_burn" not in acct.alerts_active
    _bad(acct, 12)  # sustained: 16/64 misses -> 2.5x >= 2x slow threshold
    assert "slo_slow_burn" in acct.alerts_active
    assert {k for k, _ in fired} == set(ALERT_KINDS)


def test_alert_not_armed_before_min_requests():
    acct, fired = _alert_acct()
    _bad(acct, 7)  # 100% miss but under min_requests: must stay silent
    assert acct.alerts_active == {} and fired == []
    _bad(acct, 1)
    assert "slo_fast_burn" in acct.alerts_active


def test_frontend_metrics_burn_alert_captures_slo_bundle(tmp_path, monkeypatch):
    """The frontend wiring end-to-end: a synthetic SLO-violation stream trips
    the fast burn window and the alert capture lands an slo_burn bundle."""
    from dynamo_tpu.frontend.metrics import FrontendMetrics

    monkeypatch.setenv("DYN_INCIDENT_DIR", str(tmp_path / "inc"))
    monkeypatch.setenv("DYN_ALERT_FAST_WINDOW", "8")
    monkeypatch.setenv("DYN_ALERT_MIN_REQUESTS", "8")
    monkeypatch.setenv("DYN_ALERT_SLOW_BURN", "1000")  # isolate the fast edge
    fm = FrontendMetrics()
    for _ in range(8):
        fm.slo.account(ttft_s=10.0, itl_gaps=[], output_tokens=1, ok=True)
    assert fm.slo.alerts_fired == {"slo_fast_burn": 1}
    assert fm.incidents.captured == {"slo_burn": 1}
    summaries = fm.incidents.store.list()
    assert summaries[0]["kind"] == "slo_burn"
    assert summaries[0]["trigger"]["alert"] == "slo_fast_burn"

    # The exported families carry the alert + burn state.
    text = fm.render().decode()
    assert 'dynamo_alert_active{kind="slo_fast_burn"} 1.0' in text
    assert 'dynamo_alert_fired_total{kind="slo_fast_burn"} 1.0' in text
    assert 'dynamo_slo_burn_rate{window="fast"}' in text


# -- frontend HTTP surface ---------------------------------------------------


class _FakeIncidentTelemetry:
    """WorkerTelemetryClient stand-in: one remote worker holding one bundle."""

    def __init__(self, bundle):
        self.bundle = bundle
        self.scrape_failures = {"dead-worker": 3}
        self.last_failure = {"worker": "dead-worker", "endpoint": "metrics_scrape",
                             "error": "TimeoutError", "detail": "", "ts": time.time()}

    async def collect_incidents(self):
        b = self.bundle
        return {"w-remote": [{"id": b["id"], "ts": b["ts"], "kind": b["kind"],
                              "worker": b["worker"], "trigger": b["trigger"],
                              "bytes": 100}]}

    async def fetch_incident(self, incident_id):
        return dict(self.bundle) if incident_id == self.bundle["id"] else None

    async def collect_metrics_texts(self):
        return []


async def _mock_frontend(tmp_path, monkeypatch):
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.frontend.metrics import FrontendMetrics
    from dynamo_tpu.frontend.model_manager import ModelManager

    monkeypatch.setenv("DYN_INCIDENT_DIR", str(tmp_path / "frontend-inc"))
    metrics = FrontendMetrics()
    local_id = metrics.incidents.capture("slo_burn", {"alert": "slo_fast_burn"})
    remote = dict(_bundle(kind="crash", worker="w-remote",
                          trigger={"error": "RuntimeError"}),
                  id="inc-0000000000001-9999-0001", flight=[{"kind": "crash"}])
    telemetry = _FakeIncidentTelemetry(remote)
    service = HttpService(ModelManager(), metrics=metrics, telemetry=telemetry)
    port = await service.start("127.0.0.1", 0)
    return service, f"http://127.0.0.1:{port}", local_id, remote


async def test_debug_incidents_endpoints(tmp_path, monkeypatch):
    service, base, local_id, remote = await _mock_frontend(tmp_path, monkeypatch)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/incidents") as r:
                assert r.status == 200
                doc = await r.json()
            # Frontend-local + fanned-out worker bundles, merged and sorted.
            assert doc["count"] == 2
            ids = [i["id"] for i in doc["incidents"]]
            assert set(ids) == {local_id, remote["id"]}

            # A worker-held bundle is fetchable through the frontend.
            async with s.get(f"{base}/debug/incidents/{remote['id']}") as r:
                assert r.status == 200
                bundle = await r.json()
            assert bundle["kind"] == "crash"
            assert bundle["flight"] == [{"kind": "crash"}]

            # A frontend-local bundle resolves without the fan-out.
            async with s.get(f"{base}/debug/incidents/{local_id}") as r:
                assert r.status == 200
            async with s.get(f"{base}/debug/incidents/inc-missing") as r:
                assert r.status == 404

            # Federation health: failure counters + last failure detail.
            async with s.get(f"{base}/debug/federation") as r:
                assert r.status == 200
                fed = await r.json()
            assert fed["failures"] == {"dead-worker": 3}
            assert fed["last_failure"]["error"] == "TimeoutError"
    finally:
        await service.stop()


async def test_worker_debug_server_serves_incidents(tmp_path):
    from dynamo_tpu.observability.http import WorkerDebugServer
    from dynamo_tpu.observability.metrics import EngineMetrics

    store = IncidentStore(str(tmp_path / "inc"))
    incident_id = store.save(_bundle(kind="crash"))
    server = WorkerDebugServer(EngineMetrics(worker="w-0"), incidents=store)
    port = await server.start("127.0.0.1", 0)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/debug/incidents") as r:
                assert r.status == 200
                doc = await r.json()
            assert doc["count"] == 1 and doc["incidents"][0]["id"] == incident_id
            async with s.get(f"http://127.0.0.1:{port}/debug/incidents/{incident_id}") as r:
                assert r.status == 200
                assert (await r.json())["kind"] == "crash"
            async with s.get(f"http://127.0.0.1:{port}/debug/incidents/nope") as r:
                assert r.status == 404
    finally:
        await server.close()


# -- control tower -----------------------------------------------------------


def test_top_parse_prometheus():
    from dynamo_tpu.top import parse_prometheus

    text = (
        "# HELP x y\n# TYPE x gauge\n"
        'dynamo_alert_active{kind="slo_fast_burn"} 1.0\n'
        "dynamo_output_tokens_total 42\n"
        "garbage line without value\n"
        "dynamo_bad_value notafloat\n"
    )
    samples = parse_prometheus(text)
    assert ("dynamo_alert_active", {"kind": "slo_fast_burn"}, 1.0) in samples
    assert ("dynamo_output_tokens_total", {}, 42.0) in samples
    assert all(name != "dynamo_bad_value" for name, _, _ in samples)


async def test_top_once_renders_live_mock_fleet(tmp_path, monkeypatch, capsys):
    """`python -m dynamo_tpu.top --once` against a live mock frontend: one
    frame showing alerts, burn rates, federation health, and incidents."""
    from dynamo_tpu.top import run

    service, base, local_id, _remote = await _mock_frontend(tmp_path, monkeypatch)
    # Light up the alert plane so the frame has something to show.
    for _ in range(64):
        service.metrics.slo.account(ttft_s=10.0, itl_gaps=[], output_tokens=1, ok=True)
    try:
        rc = await run(base, once=True, interval=0.0)
    finally:
        await service.stop()
    assert rc == 0
    frame = capsys.readouterr().out
    assert "fleet control tower" in frame
    assert "FIRING slo_fast_burn" in frame
    assert "burn" in frame
    assert "dead-worker" in frame and "TimeoutError" in frame
    assert local_id in frame or "inc-" in frame


def test_top_cli_once_exits_nonzero_when_frontend_unreachable(capsys):
    from dynamo_tpu.top import main

    # A port from the reserved block: connection refused immediately.
    assert main(["--url", "http://127.0.0.1:9", "--once"]) == 1
    frame = capsys.readouterr().out
    assert "!!" in frame  # degraded panels are visible, not silent


# -- vocabulary gate ---------------------------------------------------------


def test_alert_kind_vocabulary_synced():
    """Invokes the tools/ alert-kind gate (ISSUE 18 satellite): the declared
    tuples, the recording call sites, and the OBSERVABILITY.md kind tables
    must agree exactly."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "tools"))
    try:
        import check_alert_kinds
    finally:
        sys.path.pop(0)
    declared = check_alert_kinds.declared_kinds()
    assert set(declared["alert"]) == set(ALERT_KINDS)
    assert set(declared["incident"]) == set(INCIDENT_KINDS)
    assert len(declared["anomaly"]) == 5
    problems = check_alert_kinds.check(
        declared, check_alert_kinds.recorded_kinds(), check_alert_kinds.documented_kinds()
    )
    assert problems == [], "\n".join(problems)


def test_settings_env_overrides(monkeypatch):
    from dynamo_tpu.config import load_alert_settings, load_incident_settings

    monkeypatch.setenv("DYN_INCIDENT_MAX_BUNDLES", "5")
    monkeypatch.setenv("DYN_INCIDENT_COOLDOWN_S", "1.5")
    monkeypatch.setenv("DYN_ALERT_OBJECTIVE", "0.99")
    monkeypatch.setenv("DYN_ALERT_FAST_BURN", "14.4")
    inc = load_incident_settings()
    assert inc.max_bundles == 5 and inc.cooldown_s == 1.5
    alert = load_alert_settings()
    assert alert.objective == 0.99 and alert.fast_burn == 14.4
