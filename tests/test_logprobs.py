"""OpenAI logprobs: engine-level math + full HTTP schema.

The reference leaves logprobs a TODO (`lib/llm/src/protocols/openai/
completions.rs:262`); this is first-party. Semantics: log-softmax of the
RAW model logits (the model's distribution — temperature/penalties change
what is picked, not what the model believed), chosen token + top-N.
SamplingOptions.logprobs uses the +1 encoding (N = enabled, N-1
alternatives) so "chosen only" and "off" stay distinct.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.runtime.engine import Context

CFG = PRESETS["test-tiny"]
PARAMS = llama.init_params(CFG, 0)
PAGE = 4


def _core():
    runner = ModelRunner(CFG, PARAMS, num_pages=64, page_size=PAGE,
                         max_batch_size=4, prefill_bucket=16, attn_impl="reference")
    return EngineCore(runner, EngineConfig(
        num_pages=64, page_size=PAGE, max_batch_size=4,
        max_prefill_tokens=64, max_seq_len=64, decode_steps=4,
    ))


def _run(core, prompts, lp_k, max_tokens=4):
    outs = {}
    for i, p in enumerate(prompts):
        core.add_request(PreprocessedRequest(
            token_ids=list(p), sampling=SamplingOptions(temperature=0.0, logprobs=lp_k),
            stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        ), Context())
    while core.has_work:
        for seq, out in core.step():
            o = outs.setdefault(seq.seq_id, {"tokens": [], "lp": []})
            o["tokens"].extend(out.token_ids)
            if out.logprobs:
                o["lp"].extend(out.logprobs)
    return outs


def _reference_logprobs(prompt_plus_gen):
    """Full-context forward -> log-softmax at the last position."""
    tokens = list(prompt_plus_gen)
    t = len(tokens)
    pages = list(range(1, (t + PAGE - 1) // PAGE + 1))
    bt = jnp.asarray([pages], jnp.int32)
    pos = jnp.arange(t, dtype=jnp.int32)[None]
    slots = jnp.asarray([[pages[i // PAGE] * PAGE + i % PAGE for i in range(t)]], jnp.int32)
    kc, vc = llama.init_kv_cache(CFG, 64, PAGE)
    logits, _, _ = llama.forward(
        PARAMS, CFG, jnp.asarray([tokens], jnp.int32), pos, kc, vc,
        bt, slots, jnp.asarray([t - 1], jnp.int32), attn_impl="reference",
    )
    row = np.asarray(logits[0], np.float64)
    return row - np.log(np.exp(row - row.max()).sum()) - row.max()


def test_engine_logprobs_match_reference_softmax():
    """Every generated token's reported logprob equals the log-softmax of a
    naive full-context forward at that step; greedy => chosen is the top-1
    alternative; tops are sorted descending."""
    core = _core()
    prompt = [3, 5, 7, 11, 13]
    (out,) = _run(core, [prompt], lp_k=4).values()  # +1 encoding: 3 alternatives
    assert len(out["lp"]) == len(out["tokens"]) == 4
    ctx = list(prompt)
    for tok, e in zip(out["tokens"], out["lp"]):
        assert e["id"] == tok
        want = _reference_logprobs(ctx)
        np.testing.assert_allclose(e["logprob"], want[tok], rtol=2e-3, atol=2e-3)
        tops = e["top"]
        assert len(tops) == 3
        assert tops[0][0] == tok  # greedy: chosen IS the argmax
        lps = [lp for _id, lp in tops]
        assert lps == sorted(lps, reverse=True)
        for tid, tlp in tops:
            np.testing.assert_allclose(tlp, want[tid], rtol=2e-3, atol=2e-3)
        ctx.append(tok)


def test_logprobs_and_plain_requests_share_a_batch():
    """A logprobs request must not change a text-only neighbor's tokens, and
    only the requester gets entries."""
    core = _core()
    p1, p2 = [2, 4, 6, 8], [9, 7, 5, 3]
    plain_core = _core()
    plain = _run(plain_core, [p1, p2], lp_k=0)
    mixed_core = _core()
    for i, (p, k) in enumerate([(p1, 2), (p2, 0)]):
        mixed_core.add_request(PreprocessedRequest(
            token_ids=list(p), sampling=SamplingOptions(temperature=0.0, logprobs=k),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
        ), Context())
    mixed = {}
    while mixed_core.has_work:
        for seq, out in mixed_core.step():
            o = mixed.setdefault(seq.seq_id, {"tokens": [], "lp": []})
            o["tokens"].extend(out.token_ids)
            if out.logprobs:
                o["lp"].extend(out.logprobs)
    assert mixed[0]["tokens"] == plain[0]["tokens"]
    assert mixed[1]["tokens"] == plain[1]["tokens"]
    assert len(mixed[0]["lp"]) == 4
    assert mixed[1]["lp"] == []


def test_legacy_top_logprobs_survive_text_collisions():
    """Legacy completions `top_logprobs` is keyed by decoded token TEXT:
    distinct ids whose single-token decode collides (partial-UTF-8 pieces
    all render as U+FFFD) must not silently drop alternatives — the best
    logprob keeps the plain key, the rest get id-suffixed keys."""
    import asyncio

    from dynamo_tpu.frontend.openai_format import (
        _legacy_top_logprobs,
        aggregate_completion,
    )
    from dynamo_tpu.protocols.common import BackendOutput, FinishReason

    entry = {
        "id": 7, "token": "�", "logprob": -0.5,
        "top": [[7, -0.5, "�"], [9, -1.25, "�"], [11, -2.0, "ok"],
                [13, -3.0, "�"]],
    }
    (d,) = _legacy_top_logprobs([entry])
    assert len(d) == 4  # all N alternatives survive
    assert d["�"] == -0.5  # best collider keeps the plain key
    assert d["ok"] == -2.0
    assert d["�#9"] == -1.25 and d["�#13"] == -3.0
    # id-keyed fallback (no text element) never collides to begin with.
    (d2,) = _legacy_top_logprobs([{"top": [[7, -0.5], [9, -1.0]]}])
    assert d2 == {"7": -0.5, "9": -1.0}

    async def _stream():
        yield BackendOutput(text="x", cumulative_tokens=1, prompt_tokens=1,
                            finish_reason=FinishReason.STOP, logprobs=[entry])

    resp = asyncio.run(aggregate_completion("m", _stream()))
    tops = resp["choices"][0]["logprobs"]["top_logprobs"]
    assert tops == [d]


@pytest.mark.e2e
async def test_logprobs_served_http():
    """Chat + completions logprobs over the full HTTP stack (OpenAI schema)."""
    import aiohttp

    from dynamo_tpu.launch import run_local

    handles = await run_local("test-tiny", port=0, num_pages=256, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "max_tokens": 3, "temperature": 0,
                    "logprobs": True, "top_logprobs": 2,
                    "messages": [{"role": "user", "content": "hi"}]}
            r = await (await s.post(base + "/v1/chat/completions", json=body)).json()
            content = r["choices"][0]["logprobs"]["content"]
            assert len(content) == 3
            for e in content:
                assert isinstance(e["token"], str) and e["logprob"] <= 0
                assert len(e["top_logprobs"]) == 2
                assert e["top_logprobs"][0]["logprob"] >= e["top_logprobs"][1]["logprob"]

            body2 = {"model": "test-tiny", "prompt": "abc", "max_tokens": 3,
                     "temperature": 0, "logprobs": 2}
            r2 = await (await s.post(base + "/v1/completions", json=body2)).json()
            lp = r2["choices"][0]["logprobs"]
            assert len(lp["tokens"]) == len(lp["token_logprobs"]) == 3
            assert all(v <= 0 for v in lp["token_logprobs"])
            assert all(len(d) == 2 for d in lp["top_logprobs"])

            # Streaming chat: chunks carry per-token logprobs too.
            body["stream"] = True
            got_lp_chunks = 0
            async with s.post(base + "/v1/chat/completions", json=body) as resp:
                async for line in resp.content:
                    if line.startswith(b"data: ") and b"[DONE]" not in line:
                        import json as _json

                        chunk = _json.loads(line[6:])
                        if (chunk.get("choices") or [{}])[0].get("logprobs"):
                            got_lp_chunks += 1
            assert got_lp_chunks >= 3
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
