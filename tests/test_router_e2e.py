"""KV routing end-to-end: two engine workers + frontend in kv router mode.

Exercises the full loop from SURVEY.md §3 call stacks B+D: engine emits KV
stored events -> broadcaster -> subscriber -> indexer; scheduler routes a
repeated prompt to the worker that cached it; metrics plane feeds costs.
"""

import asyncio

import aiohttp

from conftest import wait_for
from dynamo_tpu.launch import run_local


async def test_kv_routed_repeat_prompt_hits_cache():
    handles = await run_local(
        "test-tiny", port=0, num_workers=2, router_mode="kv",
        num_pages=64, max_batch_size=8,
    )
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        # The watcher registered the model with a KvPushRouter pipeline.
        entry = handles["http"].manager.get("test-tiny")
        assert entry is not None and entry.aux, "kv router stack should be built"

        # 48-token prompt = 3 full pages of 16.
        body = {"model": "test-tiny", "prompt": "a" * 48, "max_tokens": 4, "temperature": 0}
        async with aiohttp.ClientSession() as s:
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200, await r.text()
                first = await r.json()

            # KV events must reach the router's indexer.
            subscriber = entry.aux[0]
            indexer = subscriber.indexer
            assert await wait_for(lambda: indexer.num_blocks >= 3), "indexer never saw KV events"

            # Count which worker currently holds blocks: exactly one.
            counts_before = indexer.worker_block_counts()
            assert len([w for w, c in counts_before.items() if c >= 3]) == 1
            (hot_worker,) = [w for w, c in counts_before.items() if c >= 3]

            # Same prompt again: must go to the same worker and hit its cache.
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200
                second = await r.json()
            assert second["choices"][0]["text"] == first["choices"][0]["text"]
            assert second["usage"]["prompt_tokens_details"]["cached_tokens"] >= 32

            # Cold different prompt: scheduler should spread to the idle worker
            # (same new-block cost, lower usage there after the cache fills).
            other = {"model": "test-tiny", "prompt": "z" * 48, "max_tokens": 4, "temperature": 0}
            async with s.post(base + "/v1/completions", json=other) as r:
                assert r.status == 200
            await wait_for(lambda: len(indexer.worker_block_counts()) == 2, timeout=3.0)
            counts_after = indexer.worker_block_counts()
            assert sum(counts_after.values()) > counts_before.get(hot_worker, 0)
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


async def test_worker_death_removes_blocks_from_index():
    handles = await run_local(
        "test-tiny", port=0, num_workers=2, router_mode="kv",
        num_pages=64, max_batch_size=8,
    )
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        entry = handles["http"].manager.get("test-tiny")
        subscriber = entry.aux[0]
        indexer = subscriber.indexer
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "prompt": "b" * 32, "max_tokens": 2, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200
        assert await wait_for(lambda: indexer.num_blocks >= 2)
        (wid,) = [w for w, c in indexer.worker_block_counts().items() if c > 0]

        # Simulate worker death: delete its instance records (lease revoke).
        store = handles["runtime"].store
        for key in list((await store.get_prefix("instances/")).keys()):
            if key.endswith(f":{wid:x}"):
                await store.delete(key)
        assert await wait_for(lambda: indexer.worker_block_counts().get(wid, 0) == 0), \
            "dead worker's blocks must leave the index"
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
