"""Async engine service tests: streaming, concurrency, cancellation."""

import asyncio

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.engine.service import JaxEngineService
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.runtime.engine import Context

CFG = PRESETS["test-tiny"]
PARAMS = llama.init_params(CFG, 0)


def make_service():
    config = EngineConfig(num_pages=64, page_size=4, max_batch_size=8, max_seq_len=128)
    runner = ModelRunner(CFG, PARAMS, num_pages=64, page_size=4, max_batch_size=8,
                         prefill_bucket=16, attn_impl="reference")
    return JaxEngineService(EngineCore(runner, config))


def req(prompt, max_tokens=5):
    return PreprocessedRequest(
        token_ids=prompt, sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens),
    ).to_dict()


async def test_stream_tokens():
    svc = make_service()
    try:
        outs = [o async for o in svc.generate(req([1, 2, 3]), Context())]
        tokens = [t for o in outs for t in o["token_ids"]]
        assert len(tokens) == 5
        assert outs[-1]["finish_reason"] == "length"
        assert outs[-1]["prompt_tokens"] == 3
    finally:
        await svc.close()


async def test_concurrent_streams():
    svc = make_service()
    try:
        async def run(prompt):
            return [t async for o in svc.generate(req(prompt, 6), Context()) for t in o["token_ids"]]

        results = await asyncio.gather(run([1, 2]), run([3, 4, 5]), run([9, 8, 7, 6]))
        assert all(len(r) == 6 for r in results)
        # Same prompt twice gives identical greedy output.
        again = await run([1, 2])
        assert again == results[0]
    finally:
        await svc.close()


async def test_cancellation_ends_stream():
    svc = make_service()
    try:
        ctx = Context()
        got = []
        async for o in svc.generate(req([1, 2, 3], max_tokens=500), ctx):
            got.append(o)
            if len(got) == 2:
                ctx.stop_generating()
        assert got[-1]["finish_reason"] in ("cancelled", "stop", "length")
        assert not svc.core.has_work
    finally:
        await svc.close()
