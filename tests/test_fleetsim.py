"""Fleet-simulation harness (dynamo_tpu/fleetsim, ISSUE 13).

Unit layers (trace determinism, scoreboard math, mocker fidelity knobs,
fleet metrics, check evaluation) run in-process; the scenario tests run
the registered fast-tier scenarios END TO END — real store server, real
frontend + KV router, real planner loop, mock-engine workers as OS
processes — and assert on the scoreboard report the same way CI operators
would.

The live scenario tests are deliberately *sync* ``def`` tests driving
``asyncio.run`` themselves: the conftest's async wrapper imposes a 60s
per-test cap that multi-worker scenarios (spawns serialize on one core)
can legitimately exceed.
"""

import asyncio
import dataclasses
import json
import time

import pytest

from dynamo_tpu.fleetsim import (
    BurstEpisode,
    Check,
    ChurnEvent,
    FleetMetrics,
    Scoreboard,
    TenantFlood,
    TraceConfig,
    WorkerTimingProfile,
    generate_trace,
    load_trace,
    save_trace,
    trace_digest,
)
from dynamo_tpu.fleetsim.scenario import SCENARIOS, run_scenario
from dynamo_tpu.fleetsim.scoreboard import RequestOutcome, SloTarget, parse_control_plane

pytestmark = pytest.mark.fleet


# -- workload plane --------------------------------------------------------


def test_trace_determinism_and_seed_sensitivity():
    cfg = TraceConfig(duration_s=20.0, base_qps=8.0, diurnal_amplitude=0.4,
                      bursts=(BurstEpisode(start_s=5.0, duration_s=2.0, rate_scale=3.0),),
                      flood=TenantFlood(tenant="heavy", start_s=8.0, duration_s=4.0, qps=20.0),
                      tenants=(("a", 0.7), ("b", 0.3)), seed=42)
    a, b = generate_trace(cfg), generate_trace(cfg)
    assert trace_digest(a) == trace_digest(b)
    assert [e.to_dict() for e in a] == [e.to_dict() for e in b]
    c = generate_trace(dataclasses.replace(cfg, seed=43))
    assert trace_digest(a) != trace_digest(c)
    # The flood stream is merged in order and carries its tenant.
    tenants = {e.tenant for e in a}
    assert "heavy" in tenants and {"a", "b"} & tenants
    assert all(a[i].t_s <= a[i + 1].t_s for i in range(len(a) - 1))
    # Shared prefix: every request starts with the same tokens.
    heads = {tuple(e.token_ids[: cfg.shared_prefix_len]) for e in a}
    assert len(heads) == 1


def test_trace_rate_shapes():
    cfg = TraceConfig(duration_s=100.0, base_qps=10.0,
                      period_shift_at_s=50.0, period_shift_scale=3.0,
                      bursts=(BurstEpisode(start_s=10.0, duration_s=5.0, rate_scale=2.0),))
    assert cfg.rate_at(5.0) == pytest.approx(10.0)
    assert cfg.rate_at(12.0) == pytest.approx(20.0)  # inside the burst
    assert cfg.rate_at(60.0) == pytest.approx(30.0)  # after the period shift
    assert cfg.rate_max() >= 30.0
    # More offered rate -> more arrivals, deterministically.
    lo = generate_trace(TraceConfig(duration_s=30.0, base_qps=2.0, seed=1))
    hi = generate_trace(TraceConfig(duration_s=30.0, base_qps=8.0, seed=1))
    assert len(hi) > len(lo) > 10


def test_trace_save_load_roundtrip(tmp_path):
    cfg = TraceConfig(duration_s=10.0, base_qps=5.0, seed=9,
                      bursts=(BurstEpisode(start_s=2.0, duration_s=1.0, rate_scale=2.0),),
                      flood=TenantFlood(tenant="x", start_s=3.0, duration_s=2.0, qps=5.0))
    events = generate_trace(cfg)
    path = tmp_path / "trace.jsonl"
    save_trace(path, cfg, events)
    cfg2, events2 = load_trace(path)
    assert cfg2 == cfg
    assert trace_digest(events2) == trace_digest(events)
    # Regenerating from the loaded config reproduces the file bit-for-bit.
    assert trace_digest(generate_trace(cfg2)) == trace_digest(events)
    # Tampering trips the digest check.
    lines = path.read_text().splitlines()
    lines[1] = lines[1].replace('"max_tokens": ', '"max_tokens": 9')
    path.write_text("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="digest"):
        load_trace(path)


# -- mocker fidelity (satellite: jitter + warm-up ramp) --------------------


def test_mock_runner_timing_scale_defaults_exact():
    from dynamo_tpu.mocker import MockRunner

    r = MockRunner(num_pages=8, page_size=16)
    state0 = r._jitter_rng.bit_generator.state
    assert all(r._timing_scale() == 1.0 for _ in range(5))
    # Defaults never touch the rng: legacy timing stays bit-identical.
    assert r._jitter_rng.bit_generator.state == state0


def test_mock_runner_jitter_seeded():
    from dynamo_tpu.mocker import MockRunner

    a = MockRunner(num_pages=8, page_size=16, seed=3, jitter=0.3)
    b = MockRunner(num_pages=8, page_size=16, seed=3, jitter=0.3)
    sa = [a._timing_scale() for _ in range(32)]
    sb = [b._timing_scale() for _ in range(32)]
    assert sa == sb  # same seed, same stream
    assert len(set(sa)) > 16  # actually stochastic
    assert all(s > 0 for s in sa)
    c = MockRunner(num_pages=8, page_size=16, seed=4, jitter=0.3)
    assert [c._timing_scale() for _ in range(32)] != sa


def test_mock_runner_warmup_ramp():
    from dynamo_tpu.mocker import MockRunner

    r = MockRunner(num_pages=8, page_size=16, warmup_s=100.0, warmup_factor=4.0)
    first = r._timing_scale()  # cold: ~4x slower
    assert first == pytest.approx(4.0, rel=0.01)
    r._warm_t0 = time.monotonic() - 50.0  # halfway through the ramp
    assert r._timing_scale() == pytest.approx(2.5, rel=0.05)
    r._warm_t0 = time.monotonic() - 200.0  # fully warm
    assert r._timing_scale() == pytest.approx(1.0, rel=0.01)


def test_mock_runner_env_overlay(monkeypatch):
    from dynamo_tpu.mocker import build_mock_core, mock_runner_env_kw

    monkeypatch.setenv("DYN_MOCK_DECODE_US_BASE", "12345")
    monkeypatch.setenv("DYN_MOCK_JITTER", "0.25")
    kw = mock_runner_env_kw()
    assert kw == {"decode_us_base": 12345.0, "jitter": 0.25}
    core = build_mock_core()
    assert core.runner.decode_us_base == 12345.0
    assert core.runner.jitter == 0.25
    # Explicit kwargs outrank the env overlay.
    core2 = build_mock_core(decode_us_base=777.0)
    assert core2.runner.decode_us_base == 777.0


def test_worker_timing_profile_env_roundtrip():
    from dynamo_tpu.mocker import mock_runner_env_kw

    p = WorkerTimingProfile(prefill_us_per_token=70.0, decode_us_base=2500.0,
                            jitter=0.1, warmup_s=2.0, warmup_factor=3.0, seed=5)
    kw = mock_runner_env_kw(env=p.to_env())
    assert kw["prefill_us_per_token"] == 70.0
    assert kw["decode_us_base"] == 2500.0
    assert kw["jitter"] == 0.1
    assert kw["warmup_s"] == 2.0
    assert kw["warmup_factor"] == 3.0
    assert kw["seed"] == 5


# -- scoreboard + checks ---------------------------------------------------


def _outcome(tenant, ttft_s, gap_s, tokens=10, ok=True, mid=False):
    return RequestOutcome(request_id="r", tenant=tenant, injected_at_s=0.0,
                          ttft_s=ttft_s, gaps=[gap_s] * 4, output_tokens=tokens,
                          ok=ok, mid_stream_failure=mid)


def test_scoreboard_slo_classification_and_fairness():
    sb = Scoreboard(SloTarget(ttft_ms=100.0, itl_p99_ms=20.0))
    for _ in range(8):
        sb.observe(_outcome("light", ttft_s=0.05, gap_s=0.01))  # attains
    sb.observe(_outcome("light", ttft_s=0.5, gap_s=0.01))  # TTFT blown
    for _ in range(4):
        sb.observe(_outcome("heavy", ttft_s=0.05, gap_s=0.05))  # ITL blown
    sb.observe(_outcome("heavy", ttft_s=0.05, gap_s=0.01))  # attains
    sb.observe(_outcome("heavy", ttft_s=0.0, gap_s=0.0, ok=False, mid=True))
    rep = sb.report(duration_s=10.0)
    assert rep["requests"] == {"total": 15, "ok": 14, "error": 1,
                               "mid_stream_failure": 1}
    assert rep["tenants"]["light"]["goodput_frac"] == pytest.approx(8 / 9, abs=1e-4)
    assert rep["tenants"]["heavy"]["goodput_frac"] == pytest.approx(1 / 6, abs=1e-4)
    assert rep["tenant_fairness"] == pytest.approx((1 / 6) / (8 / 9), abs=1e-4)
    assert rep["goodput_frac_at_slo"] == pytest.approx(9 / 15)
    assert rep["goodput_tokens_per_s_at_slo"] == pytest.approx(9.0)  # 90 tok / 10 s
    assert set(rep["ttft_ms"]) == {"p50", "p95", "p99", "p99_9"}
    # Failed requests must not pollute the latency estimators.
    assert rep["ttft_ms"]["p99"] < 600.0


def test_check_dotted_paths():
    rep = {"a": {"b": {"c": 3.0}}, "x": 1}
    assert Check("a.b.c", ">=", 3.0).evaluate(rep)["ok"]
    assert not Check("a.b.c", ">", 3.0).evaluate(rep)["ok"]
    missing = Check("a.b.zzz", ">=", 0.0).evaluate(rep)
    assert not missing["ok"] and missing["actual"] is None
    assert Check("x", "==", 1).evaluate(rep)["ok"]


def test_parse_control_plane_metrics_text():
    text = "\n".join([
        "# HELP dynamo_client_breaker_state state",
        'dynamo_client_breaker_state{endpoint="generate",instance="i1"} 2.0',
        'dynamo_client_breaker_state{endpoint="generate",instance="i2"} 0.0',
        'dynamo_client_watch_restarts_total{endpoint="generate"} 3.0',
        'dynamo_engine_prefill_requeues_total{worker="w1"} 5.0',
        'dynamo_engine_steps_total{worker="w1"} 100.0',
        'dynamo_engine_steps_total{worker="w2"} 90.0',
        # Attribution families fold across workers, keyed by cause/kind.
        'dynamo_engine_lost_time_seconds_total{cause="gap",worker="w1"} 1.5',
        'dynamo_engine_lost_time_seconds_total{cause="gap",worker="w2"} 0.5',
        'dynamo_engine_lost_time_seconds_total{cause="queue",worker="w1"} 0.25',
        'dynamo_engine_step_time_seconds_total{kind="wall",worker="w1"} 4.0',
        'dynamo_engine_step_time_seconds_total{kind="dispatch",worker="w1"} 3.0',
        'dynamo_anomaly_active{kind="recompile_storm",worker="w2"} 1.0',
        'dynamo_anomaly_fired_total{kind="recompile_storm",worker="w2"} 2.0',
        # HA control plane: failover/retry view + reconstruction signals.
        "dynamo_router_index_resyncs_total 4.0",
        "dynamo_store_failovers_total 1.0",
        "dynamo_store_client_op_retries_total 2.0",
        'dynamo_frontend_cached_prompt_tokens_total{model="a"} 64.0',
        'dynamo_frontend_cached_prompt_tokens_total{model="b"} 16.0',
        "not_a_metric",
    ])
    snap = parse_control_plane(text)
    assert snap["breaker_open"] == 1.0
    assert snap["watch_restarts"] == 3.0
    assert snap["prefill_requeues"] == 5.0
    assert snap["engine_registries"] == 2.0
    assert snap["lost_time_s"] == {"gap": 2.0, "queue": 0.25}
    assert snap["step_time_s"] == {"wall": 4.0, "dispatch": 3.0}
    assert snap["anomaly_active"] == {"recompile_storm": 1.0}
    assert snap["anomaly_fired"] == {"recompile_storm": 2.0}
    assert snap["router_resyncs"] == 4.0
    assert snap["store_failovers"] == 1.0
    assert snap["store_client_retries"] == 2.0
    assert snap["cached_tokens"] == 80.0  # summed across models


def test_scoreboard_loss_accounting_and_anomaly_report():
    """Fleet-wide time-loss accounting (ISSUE 15): the report explains
    non-compute wall (wall + gap - dispatch) with the step-side causes,
    ranks the top losses, and surfaces the sentinel's counters."""
    sb = Scoreboard(SloTarget())
    sb.lost_time_s = {"gap": 2.0, "pages": 1.0, "queue": 5.0, "drain": 0.0}
    sb.step_time_s = {"wall": 100.0, "dispatch": 97.0, "gap": 3.0}
    sb.anomaly_fired = {"recompile_storm": 2.0}
    sb.anomaly_active_max = {"recompile_storm": 1.0, "goodput_drop": 0.0}

    loss = sb.loss_accounting()
    assert loss["noncompute_wall_s"] == pytest.approx(6.0)  # 100 + 3 - 97
    # queue waits happen before the step loop: excluded from step coverage.
    assert loss["step_lost_s"] == pytest.approx(3.0)  # gap 2 + pages 1
    assert loss["lost_s_total"] == pytest.approx(8.0)
    assert loss["unattributed_frac"] == pytest.approx(0.5)  # (6 - 3) / 6
    assert loss["top_loss_causes"] == [
        {"cause": "queue", "seconds": 5.0},
        {"cause": "gap", "seconds": 2.0},
        {"cause": "pages", "seconds": 1.0},
    ]  # zero-second causes never pad the ranking

    rep = sb.report(duration_s=10.0)
    assert rep["loss"]["unattributed_frac"] == pytest.approx(0.5)
    assert rep["anomalies"]["fired_total"] == 2
    assert rep["anomalies"]["by_kind"] == {"recompile_storm": 2}
    assert rep["anomalies"]["active_peak"] == {"recompile_storm": 1}

    # An empty ledger (no scrape landed) reports cleanly, never divides by 0.
    empty = Scoreboard(SloTarget()).loss_accounting()
    assert empty["noncompute_wall_s"] == 0.0
    assert empty["unattributed_frac"] == 0.0
    assert empty["top_loss_causes"] == []


def test_fleet_metrics_sync_and_render():
    fm = FleetMetrics()
    fm.sync_report({
        "goodput_frac_at_slo": 0.9, "goodput_tokens_per_s_at_slo": 120.0,
        "tenant_fairness": 0.8,
        "requests": {"ok": 9, "error": 1, "mid_stream_failure": 1},
        "tenants": {"light": {"goodput_frac": 1.0}},
        "ttft_ms": {"p50": 10.0, "p99": 40.0},
        "itl_ms": {"p50": 2.0},
        "fleet": {"spawns": 3, "kills": 1, "live": 2},
    })
    text = fm.render().decode()
    assert "dynamo_fleet_goodput_frac_at_slo 0.9" in text
    assert 'dynamo_fleet_requests{outcome="ok"} 9.0' in text
    assert 'dynamo_fleet_tenant_goodput_frac{tenant="light"} 1.0' in text
    assert 'dynamo_fleet_ttft_quantile_seconds{quantile="p99"} 0.04' in text
    assert "dynamo_fleet_workers_live 2.0" in text
    assert 'dynamo_fleet_lifecycle_events{event="kills"} 1.0' in text


def test_cache_rate_from_profile(monkeypatch):
    """Satellite: the router's cache-aware rate comes from the profiled
    prefill throughput, env override outranks it, default is the fallback."""
    import types

    from dynamo_tpu.planner.core import WorkerProfile
    from dynamo_tpu.sched import configure_cache_aware

    prof = WorkerProfile(prefill_tokens_per_sec=55555.0)

    cfg = types.SimpleNamespace(profile=None)
    configure_cache_aware(cfg, {"DYN_CACHE_AWARE": "1"}, profile=prof)
    assert cfg.cache_rate_tokens_per_s == 55555.0

    # configure_attainment already armed config.profile: reuse it.
    cfg2 = types.SimpleNamespace(profile=prof)
    configure_cache_aware(cfg2, {"DYN_CACHE_AWARE": "1"})
    assert cfg2.cache_rate_tokens_per_s == 55555.0

    # An explicit operator rate outranks the profile.
    cfg3 = types.SimpleNamespace(profile=prof)
    configure_cache_aware(
        cfg3, {"DYN_CACHE_AWARE": "1", "DYN_CACHE_AWARE_RATE_TOKENS_PER_S": "9000"})
    assert cfg3.cache_rate_tokens_per_s == 9000.0

    # No profile anywhere: the settings default.
    cfg4 = types.SimpleNamespace(profile=None)
    configure_cache_aware(cfg4, {"DYN_CACHE_AWARE": "1"})
    assert cfg4.cache_rate_tokens_per_s == 20000.0

    # Master toggle off: untouched.
    cfg5 = types.SimpleNamespace(profile=prof)
    configure_cache_aware(cfg5, {})
    assert not hasattr(cfg5, "cache_rate_tokens_per_s")


def test_scenario_registry_and_dry_run():
    assert {"smoke", "burst_absorb", "tenant_flood", "kill_midstream",
            "incident_capture", "store_failover", "frontend_restart",
            "period_shift", "fleet_accept", "diurnal_soak"} <= set(SCENARIOS)
    assert SCENARIOS["diurnal_soak"].tier == "soak"
    rep = asyncio.run(run_scenario(SCENARIOS["fleet_accept"], dry_run=True))
    rep2 = asyncio.run(run_scenario(SCENARIOS["fleet_accept"], dry_run=True))
    # Same seed -> same trace -> same digest: the determinism contract.
    assert rep["trace"]["digest"] == rep2["trace"]["digest"]
    assert rep["trace"]["events"] > 0
    assert rep["passed"] is None  # dry runs don't adjudicate


def test_fleetsim_cli_list_and_trace(tmp_path, capsys):
    from dynamo_tpu.fleetsim.__main__ import main

    assert main(["list"]) == 0
    out = capsys.readouterr().out
    assert "fleet_accept" in out and "soak" in out

    path = tmp_path / "smoke.jsonl"
    assert main(["trace", "smoke", "--out", str(path)]) == 0
    assert main(["trace", "--replay", str(path)]) == 0
    capsys.readouterr()  # drain replay summary
    cfg, events = load_trace(path)
    assert cfg.seed == SCENARIOS["smoke"].trace.seed
    assert trace_digest(events) == trace_digest(generate_trace(cfg))
    assert main(["run", "nope", "--dry-run"]) == 2


# -- live scenarios (real control plane + worker processes) ----------------
#
# Sync tests on purpose — see module docstring. Each runs one registered
# fast-tier scenario exactly as `python -m dynamo_tpu.fleetsim run <name>`
# would and asserts the scenario's own checks passed.


def _run(name: str) -> dict:
    report = asyncio.run(run_scenario(SCENARIOS[name]))
    assert report["passed"], json.dumps(report.get("checks"), indent=2)
    return report


@pytest.mark.e2e
def test_scenario_burst_absorb_live():
    """A 4x burst must not blow the ITL tail: decode cadence holds while
    the prefill backlog drains through chunked steps."""
    report = _run("burst_absorb")
    assert report["itl_ms"]["p99"] <= 50.0
    assert report["requests"]["error"] == 0


@pytest.mark.e2e
def test_scenario_tenant_flood_live():
    """A heavy-tenant flood cannot starve the light tenant below the
    attainment floor (admission plane + quotas armed via scenario env)."""
    report = _run("tenant_flood")
    assert report["tenants"]["light"]["goodput_frac"] >= 0.6
    assert report["tenants"]["heavy"]["requests"] > report["tenants"]["light"]["requests"]


@pytest.mark.e2e
def test_scenario_kill_midstream_live():
    """SIGKILL of the stream-holding worker: structured mid_stream_failure
    SSEs for the severed streams, the survivor keeps completing requests."""
    report = _run("kill_midstream")
    assert report["requests"]["mid_stream_failure"] >= 1
    assert report["requests"]["ok"] >= 3
    assert report["fleet"]["kills"] == 1
    assert report["fleet"]["live"] == 1


@pytest.mark.e2e
def test_scenario_incident_capture_live():
    """Deterministic engine-step crash (fault plane): every worker's 40th
    step raises, the black-box recorder lands crash bundles in the incident
    store, and the frontend serves them back via /debug/incidents/{id}."""
    report = _run("incident_capture")
    assert report["incidents"]["bundles"] >= 1
    assert report["incidents"]["kinds"].get("crash", 0) >= 1
    assert report["incidents"]["fetch_ok"] == 1
    assert report["requests"]["ok"] >= 3


@pytest.mark.e2e
def test_scenario_store_failover_live():
    """Kill-the-leader gate (HA control plane): SIGKILL the store leader of
    a 3-replica cluster mid-trace. A follower must promote under the epoch
    fence inside the budget, no declarative key may be lost, no worker may
    lose its registration, and the serving plane keeps scoring."""
    report = _run("store_failover")
    ha = report["store_ha"]
    assert ha["declarative_lost"] == 0
    assert ha["worker_deregistrations"] == 0
    assert 0 < ha["failover_s"] <= 5.0
    assert ha["epoch"] >= 2
    assert report["requests"]["ok"] >= 10


@pytest.mark.e2e
def test_scenario_frontend_restart_live():
    """Frontend reconstruction gate: bounce the frontend mid-trace. The
    replacement rebuilds the prefix index from worker KV-event snapshots
    (resyncs observed across the bounce), recovers warm routing (cache hits
    on the fresh registry), and no stream wedges."""
    report = _run("frontend_restart")
    assert report["frontend"]["bounces"] >= 1
    assert report["frontend"]["resyncs"] >= 1
    assert report["control_plane"]["cached_tokens_final"] > 0
    assert report["requests"]["ok"] >= 8


@pytest.mark.e2e
def test_scenario_period_shift_live():
    """Planner scales the decode fleet up into the 5x period shift and back
    down in the cooldown drain, with every decision in the report."""
    report = _run("period_shift")
    assert report["planner"]["max_decode_workers"] >= 2
    assert report["planner"]["final_decode_workers"] <= 1
    assert report["fleet"]["scale_ups"] >= 1
    assert report["fleet"]["scale_downs"] >= 1
    assert all("t_s" in d for d in report["planner"]["decisions"])


@pytest.mark.e2e
def test_scenario_fleet_accept_live(tmp_path):
    """ISSUE 13 acceptance gate: >= 8 worker processes against the real
    frontend/router/store with chaos armed, goodput + fairness + lifecycle
    accounting asserted, trace digest deterministic."""
    scn = SCENARIOS["fleet_accept"]
    assert scn.workers >= 8 and scn.faults
    out = tmp_path / "accept.json"
    report = asyncio.run(run_scenario(scn, report_path=str(out)))
    assert report["passed"], json.dumps(report.get("checks"), indent=2)
    assert report["fleet"]["spawns"] >= 9
    assert report["fleet"]["kills"] >= 1
    assert report["goodput_frac_at_slo"] >= 0.5
    assert report["tenant_fairness"] >= 0.5
    assert len(report["tenants"]) == 2
    # The written report round-trips and carries the deterministic digest.
    disk = json.loads(out.read_text())
    dry = asyncio.run(run_scenario(scn, dry_run=True))
    assert disk["trace"]["digest"] == dry["trace"]["digest"]
    # The scoreboard report feeds the dynamo_fleet_* families directly.
    fm = FleetMetrics()
    fm.sync_report(disk)
    assert b"dynamo_fleet_goodput_frac_at_slo" in fm.render()


@pytest.mark.slow
@pytest.mark.e2e
def test_scenario_diurnal_soak():
    """The hour-scale diurnal soak (slow tier): planner-owned fleet under
    diurnal load with a mid-cycle flood and chaos armed."""
    report = asyncio.run(run_scenario(SCENARIOS["diurnal_soak"]))
    assert report["passed"], json.dumps(report.get("checks"), indent=2)
