"""HA control plane: store replication, epoch-fenced failover, client HA.

Every test stands up a real replicated cluster — N ``StoreServer`` instances
on loopback ports with ``attach_replication`` coordinators — and exercises
the wire protocol end to end: log-shipping byte-exactness, the epoch fence
against a stale ex-leader, lease continuity across a leader kill,
multi-endpoint client failover with watch re-arm, promotion determinism,
and WAL durability on a promoted follower.
"""

import asyncio
import socket

import pytest

from dynamo_tpu.runtime.discovery import MemoryStore, WatchEventType
from dynamo_tpu.runtime.persist import PersistentStore
from dynamo_tpu.runtime.replication import attach_replication, replica_snapshot
from dynamo_tpu.runtime.store_server import (
    StoreClient,
    StoreServer,
    store_client_snapshot,
)

pytestmark = pytest.mark.store_ha


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _cluster(n: int, stores=None, *, promote_after_s=0.3, poll_s=0.05, **knobs):
    """N replicas on loopback; returns (peers, servers, coords)."""
    ports = [_free_port() for _ in range(n)]
    peers = [f"tcp://127.0.0.1:{p}" for p in ports]
    servers, coords = [], []
    for i, port in enumerate(ports):
        store = stores[i] if stores is not None else MemoryStore()
        srv = await StoreServer(store, host="127.0.0.1", port=port).start()
        coord = attach_replication(
            srv, peers, i, promote_after_s=promote_after_s, poll_s=poll_s, **knobs
        )
        await coord.start()
        servers.append(srv)
        coords.append(coord)
    return peers, servers, coords


async def _shutdown(servers, client=None):
    if client is not None:
        await client.close()
    for srv in servers:
        if srv._server is not None:
            await srv.close()


async def _wait(predicate, timeout=8.0, every=0.05, msg="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        result = predicate()
        if asyncio.iscoroutine(result):
            result = await result
        if result:
            return
        assert loop.time() < deadline, f"timed out waiting for {msg}"
        await asyncio.sleep(every)


async def _converged(leader_srv, follower_srv) -> bool:
    return await leader_srv.store.get_prefix("") == await follower_srv.store.get_prefix("")


# -- replication semantics ---------------------------------------------------


async def test_mutation_storm_replicates_byte_exact():
    """Log shipping: after a storm of puts/overwrites/deletes/leases, every
    follower's full keyspace is byte-identical to the leader's."""
    peers, servers, coords = await _cluster(3)
    client = StoreClient.from_url(",".join(peers))
    try:
        lease = await client.create_lease(30.0)
        for i in range(40):
            await client.put(f"cfg/{i % 13}", f"v{i}".encode())
        for i in range(0, 13, 3):
            await client.delete(f"cfg/{i}")
        assert await client.put_if_absent("once", b"first")
        assert not await client.put_if_absent("once", b"second")  # not recorded twice
        await client.put(f"instances/w:{lease.id:x}", b"\x00\xffbin", lease_id=lease.id)
        await client.keep_alive(lease.id)

        want = await servers[0].store.get_prefix("")
        assert want["once"] == b"first"
        await _wait(
            lambda: coords[1].seq == coords[0].seq and coords[2].seq == coords[0].seq,
            msg="log fully shipped",
        )
        for i in (1, 2):
            assert await servers[i].store.get_prefix("") == want
            # The lease-bound key is lease-bound on the follower too.
            assert servers[i].store._key_lease[f"instances/w:{lease.id:x}"] == lease.id
        assert coords[0].epoch == coords[1].epoch == coords[2].epoch == 1
    finally:
        await _shutdown(servers, client)


async def test_epoch_fence_demotes_stale_leader_and_discards_divergence():
    """Split-brain heal: a usurper promotion bumps the epoch; the stale
    ex-leader is fenced on its next peer poll, demotes, resyncs from the new
    leader, and its divergent write vanishes — never two leaders at rest."""
    peers, servers, coords = await _cluster(2, poll_s=0.05, promote_after_s=30)
    client = StoreClient.from_url(",".join(peers))
    try:
        await client.put("cfg/shared", b"v1")
        await _wait(lambda: _converged(servers[0], servers[1]), msg="initial convergence")

        # Force a usurper: the follower promotes while the old leader lives.
        await coords[1].promote()
        assert coords[1].role == "leader" and coords[1].epoch == 2

        # The stale leader accepts a divergent write (epoch-1 world)...
        await servers[0]._execute("put", {"key": "cfg/divergent", "value": b"stale"})
        assert await servers[0].store.get("cfg/divergent") == b"stale"

        # ...until the watchdog sees epoch 2 and fences it.
        await _wait(lambda: coords[0].role == "follower", msg="stale leader demotion")
        await _wait(lambda: coords[0].epoch == 2, msg="ex-leader resync to epoch 2")
        # Resync reconciled away the divergent write; real state survived.
        await _wait(
            lambda: servers[0].store._data.get("cfg/divergent") is None,
            msg="divergent write discarded",
        )
        assert await servers[0].store.get("cfg/shared") == b"v1"
        assert await servers[1].store.get("cfg/divergent") is None
        assert [c.role for c in coords].count("leader") == 1
    finally:
        await _shutdown(servers, client)


async def test_stale_follower_handshake_is_fence_too():
    """The replicate handshake fences in both directions: a follower that has
    seen a higher epoch demotes the leader it dials."""
    peers, servers, coords = await _cluster(2, promote_after_s=30)
    try:
        await _wait(lambda: coords[1].leader_url == peers[0] and coords[1].epoch == 1,
                    msg="follower subscribed")
        # Simulate the follower having witnessed a newer epoch elsewhere.
        coords[1].epoch = 5
        with pytest.raises(Exception):
            await coords[1]._follow(peers[0])
        await _wait(lambda: coords[0].role == "follower", msg="leader fenced by handshake")
    finally:
        await _shutdown(servers)


async def test_lease_continuity_across_handoff():
    """Workers do NOT deregister on failover: replicated keepalives re-arm the
    lease on followers, promotion grants a grace TTL, and the owner's next
    keepalive lands on the new leader."""
    peers, servers, coords = await _cluster(3, promote_after_s=0.3, poll_s=0.05)
    client = StoreClient.from_url(",".join(peers))
    try:
        lease = await client.create_lease(1.5)
        key = f"instances/worker:{lease.id:x}"
        await client.put(key, b"registered", lease_id=lease.id)
        await client.keep_alive(lease.id)
        await _wait(lambda: _converged(servers[0], servers[1]), msg="lease replication")

        kill_at = asyncio.get_running_loop().time()
        await servers[0].close()
        await _wait(lambda: any(c.role == "leader" for c in coords[1:]), msg="promotion")

        # The instance key must survive past the original TTL measured from
        # the kill — promotion grace + clock-relative adoption guarantee it.
        await client.keep_alive(lease.id)  # client failover path
        await asyncio.sleep(max(0.0, kill_at + 1.7 - asyncio.get_running_loop().time()))
        assert (await client.get(key)) == b"registered"

        # And the lease still expires honestly once keepalives really stop.
        new_leader = next(s for s, c in zip(servers[1:], coords[1:]) if c.role == "leader")
        await asyncio.sleep(2.0)
        assert await new_leader.store.get(key) is None
    finally:
        await _shutdown(servers, client)


async def test_promotion_determinism_rank_order():
    """Election rank is the total order (epoch, seq, -index): only the
    freshest reachable follower answers yes; ties break to the lowest index."""
    ports = [_free_port() for _ in range(3)]
    peers = [f"tcp://127.0.0.1:{p}" for p in ports]
    servers, coords = [], []
    for i in (1, 2):  # peers[0] (the bootstrap leader) is never started
        srv = await StoreServer(MemoryStore(), host="127.0.0.1", port=ports[i]).start()
        coord = attach_replication(srv, peers, i, promote_after_s=60, poll_s=0.05)
        await coord.start()
        servers.append(srv)
        coords.append(coord)
    try:
        c1, c2 = coords
        c1.seq, c2.seq = 5, 9
        assert await c2._should_promote()  # freshest log wins
        assert not await c1._should_promote()
        c1.seq = 9
        assert await c1._should_promote()  # tie: lower index wins
        assert not await c2._should_promote()
        c1.epoch = 1
        assert await c1._should_promote()  # higher epoch dominates seq
        c2.seq = 10_000
        assert not await c2._should_promote()
    finally:
        await _shutdown(servers)


# -- client HA ---------------------------------------------------------------


async def test_client_failover_retries_idempotent_ops_once():
    """A multi-endpoint client rides a leader SIGKILL: the in-flight/next op
    reconnects via who_leads discovery and replays exactly once, counted in
    dynamo_store_client_op_retries_total's source."""
    peers, servers, coords = await _cluster(2, promote_after_s=0.2, poll_s=0.05)
    client = StoreClient.from_url(",".join(peers))
    try:
        await client.put("cfg/a", b"1")
        await _wait(lambda: coords[1].seq == coords[0].seq, msg="follower caught up")
        retries_before = store_client_snapshot()["retries"]
        await servers[0].close()
        assert await client.get("cfg/a") == b"1"  # survived via retry+failover
        assert store_client_snapshot()["retries"] == retries_before + 1
        info = await client.who_leads()
        assert info["role"] == "leader" and info["epoch"] == 2
        assert store_client_snapshot()["epoch"] >= 2
        await client.put("cfg/b", b"2")  # mutations land on the new leader
        assert await client.get("cfg/b") == b"2"
    finally:
        await _shutdown(servers, client)


async def test_client_raises_when_no_leader_within_window():
    """With every replica dead, the client gives up after the failover window
    instead of hanging — and non-idempotent ops are never silently replayed."""
    peers, servers, coords = await _cluster(2)
    client = StoreClient.from_url(",".join(peers))
    client._failover_timeout_s = 0.5
    try:
        await client.put("cfg/a", b"1")
        for srv in servers:
            await srv.close()
        with pytest.raises(ConnectionError):
            await client.get("cfg/a")
    finally:
        await _shutdown(servers, client)


async def test_watch_rearms_across_failover_with_synthetic_deletes():
    """An HA watch survives the death of the replica serving it: it re-arms
    against a live replica, replays current state, and synthesizes DELETE
    events for keys that vanished while it was dark."""
    peers, servers, coords = await _cluster(2, promote_after_s=0.2, poll_s=0.05)
    client = StoreClient.from_url(",".join(peers))
    events: list = []

    async def _watch():
        async for ev in client.watch_prefix("w/"):
            events.append(ev)

    task = asyncio.create_task(_watch())
    try:
        await client.put("w/keep", b"k")
        await client.put("w/drop", b"d")
        await _wait(lambda: len(events) >= 2, msg="initial watch events")
        await _wait(lambda: coords[1].seq == coords[0].seq, msg="follower caught up")
        # The watch walks endpoints from index 0, so it is held by replica 0.
        await servers[0].close()
        await _wait(lambda: coords[1].role == "leader", msg="promotion")
        await client.delete("w/drop")  # happens while the watch is dark
        await client.put("w/new", b"n")
        await _wait(
            lambda: any(e.type is WatchEventType.DELETE and e.key == "w/drop" for e in events),
            msg="synthetic DELETE for w/drop",
        )
        await _wait(
            lambda: any(e.type is WatchEventType.PUT and e.key == "w/new" for e in events),
            msg="post-failover PUT event",
        )
        # Re-announced state after re-arm never invents keys.
        assert {e.key for e in events} <= {"w/keep", "w/drop", "w/new"}
    finally:
        task.cancel()
        await _shutdown(servers, client)


# -- durability --------------------------------------------------------------


async def test_wal_replay_on_promoted_follower(tmp_path):
    """A PersistentStore-backed follower WALs every replicated record; after
    promotion and a crash, replay recovers all declarative keys — including
    ones written both before and after the handoff."""
    wal = tmp_path / "follower.wal"
    stores = [MemoryStore(), await PersistentStore.open(wal)]
    peers, servers, coords = await _cluster(
        2, stores=stores, promote_after_s=0.2, poll_s=0.05
    )
    client = StoreClient.from_url(",".join(peers))
    try:
        await client.put("deployments/a", b"spec-a")
        lease = await client.create_lease(30.0)
        await client.put(f"instances/w:{lease.id:x}", b"eph", lease_id=lease.id)
        await _wait(lambda: _converged(servers[0], servers[1]), msg="follower caught up")

        await servers[0].close()
        await _wait(lambda: coords[1].role == "leader", msg="promotion")
        await client.put("deployments/b", b"spec-b")  # written by the new leader
    finally:
        await _shutdown(servers, client)

    replayed = await PersistentStore.open(wal)
    try:
        assert await replayed.get("deployments/a") == b"spec-a"
        assert await replayed.get("deployments/b") == b"spec-b"
        # Lease-bound keys stay ephemeral: their owner died with the cluster.
        assert await replayed.get_prefix("instances/") == {}
    finally:
        replayed.close_log()
        await replayed.close()


# -- dormancy ----------------------------------------------------------------


async def test_single_replica_mode_stays_dormant():
    """No replica list -> no coordinator: who_leads answers 'single', the
    client takes the pre-HA path, and no replication machinery exists."""
    server = await StoreServer(MemoryStore(), host="127.0.0.1", port=0).start()
    client = StoreClient.from_url(f"tcp://127.0.0.1:{server.port}")
    try:
        assert server.repl is None
        assert not client._multi
        await client.put("k", b"v")
        assert await client.get("k") == b"v"
        info = await client.who_leads()
        assert info == {"role": "single", "leader": None, "epoch": 0, "seq": 0}
    finally:
        await _shutdown([server], client)


async def test_replica_snapshot_reflects_local_coordinator():
    peers, servers, coords = await _cluster(2, promote_after_s=30)
    try:
        snap = replica_snapshot()
        assert snap is not None
        assert snap["role"] in ("leader", "follower")
        assert {"epoch", "seq", "lag_s", "failovers"} <= set(snap)
    finally:
        await _shutdown(servers)


async def test_debug_store_endpoint_serves_ha_view():
    """GET /debug/store: the operator's one-stop HA view — hosted replica
    state, client failover ledger, router resync counter — answered from
    process-local snapshots (no store RPC, so it works mid-failover too)."""
    import aiohttp

    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.frontend.model_manager import ModelManager

    peers, servers, coords = await _cluster(2, promote_after_s=30)
    service = HttpService(ModelManager())
    port = await service.start("127.0.0.1", 0)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/debug/store") as r:
                assert r.status == 200
                doc = await r.json()
        assert doc["replica"] is not None
        assert doc["replica"]["role"] in ("leader", "follower")
        assert {"epoch", "seq", "lag_s", "failovers"} <= set(doc["replica"])
        assert {"role", "epoch", "failovers", "retries"} <= set(doc["client"])
        assert doc["router"]["resyncs"] >= 0
    finally:
        await service.stop()
        await _shutdown(servers)
