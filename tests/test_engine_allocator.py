"""Unit tests for the HBM page allocator (G1 tier): free list, prefix cache,
refcounts, LRU eviction, and KV event emission."""

import pytest

from dynamo_tpu.engine.allocator import OutOfPagesError, PageAllocator
from dynamo_tpu.protocols.kv import KvCacheEvent


def collect_events():
    events: list[KvCacheEvent] = []
    return events, events.append


def test_allocate_release_roundtrip():
    alloc = PageAllocator(num_pages=8, page_size=4)
    pages = alloc.allocate(7)
    assert sorted(pages) == list(range(1, 8))  # page 0 reserved
    with pytest.raises(OutOfPagesError):
        alloc.allocate(1)
    alloc.release(pages)
    assert alloc.num_free() == 7


def test_prefix_cache_hit_and_events():
    events, cb = collect_events()
    alloc = PageAllocator(num_pages=8, page_size=4, on_event=cb)
    [p1] = alloc.allocate(1)
    alloc.commit(p1, block_hash=111, parent_hash=None, token_ids=(1, 2, 3, 4))
    assert len(events) == 1 and events[0].stored[0].block_hash == 111
    alloc.release([p1])  # becomes evictable prefix cache

    matched = alloc.match_prefix([111, 222])
    assert matched == [p1]  # stops at first miss
    st = alloc.stats()
    assert st.hits == 1 and st.misses == 1
    alloc.release(matched)


def test_lru_eviction_emits_removed():
    events, cb = collect_events()
    alloc = PageAllocator(num_pages=4, page_size=4, on_event=cb)
    pages = alloc.allocate(3)
    for i, p in enumerate(pages):
        alloc.commit(p, block_hash=100 + i, parent_hash=None)
    alloc.release(pages)
    # All 3 cached; allocating 2 must evict the 2 least recently used (100, 101).
    alloc.allocate(2)
    removed = [r.block_hash for e in events for r in e.removed]
    assert removed == [100, 101]
    # 102 still matchable.
    assert len(alloc.match_prefix([102])) == 1


def test_match_touches_lru_order():
    alloc = PageAllocator(num_pages=4, page_size=4)
    pages = alloc.allocate(3)
    for i, p in enumerate(pages):
        alloc.commit(p, block_hash=200 + i, parent_hash=None)
    alloc.release(pages)
    # Touch 200: it becomes MRU; eviction must take 201 first.
    m = alloc.match_prefix([200])
    alloc.release(m)
    alloc.allocate(1)
    assert alloc.match_prefix([201]) == []  # evicted
    assert len(alloc.match_prefix([200])) == 1  # survived


def test_duplicate_commit_not_cached_twice():
    alloc = PageAllocator(num_pages=8, page_size=4)
    [a, b] = alloc.allocate(2)
    alloc.commit(a, block_hash=7, parent_hash=None)
    alloc.commit(b, block_hash=7, parent_hash=None)  # concurrent duplicate
    alloc.release([a, b])
    # Only one page holds hash 7; the duplicate went back to the free list.
    assert alloc.stats().cached_pages == 1
    assert alloc.stats().free_pages == 6


def test_shared_page_refcounting():
    alloc = PageAllocator(num_pages=8, page_size=4)
    [p] = alloc.allocate(1)
    alloc.commit(p, block_hash=5, parent_hash=None)
    alloc.release([p])
    m1 = alloc.match_prefix([5])
    m2 = alloc.match_prefix([5])
    assert m1 == m2 == [p]
    alloc.release(m1)
    # Still referenced by m2: not evictable.
    assert alloc.stats().cached_pages == 0 and alloc.stats().active_pages == 1
    alloc.release(m2)
    assert alloc.stats().cached_pages == 1


def test_clear_cache():
    events, cb = collect_events()
    alloc = PageAllocator(num_pages=8, page_size=4, on_event=cb)
    pages = alloc.allocate(3)
    for i, p in enumerate(pages):
        alloc.commit(p, block_hash=300 + i, parent_hash=None)
    alloc.release(pages)
    n = alloc.clear_cache()
    assert n == 3
    assert alloc.num_free() == 7
    removed = [r.block_hash for e in events for r in e.removed]
    assert sorted(removed) == [300, 301, 302]
