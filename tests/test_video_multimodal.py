"""Video multimodal pipeline (VERDICT r4 item 7).

Golden: a Qwen2-VL video (temporal grid t>1) must reproduce HF logits —
pinning per-frame block-diagonal tower attention, the temporal patchify,
video M-RoPE coords (t axis advances per temporal group), and video
placeholder substitution. E2E: a served video_url request produces tokens
and the frame-count/placeholder accounting holds, for both the Qwen2-VL
native path and the LLaVA frame-stack path.

Reference: `examples/multimodal/components/video_encode_worker.py`,
`video_decode_worker.py`, `video_processor.py` (frame sampling -> encode ->
embeddings handed to prefill).
"""

import io

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.loader import load_vlm  # noqa: E402
from dynamo_tpu.models.qwen2_vl import (  # noqa: E402
    TEST_TINY_QWEN2VL_VISION,
    encode_qwen2vl,
    mrope_position_ids,
    patchify_frames,
)
from tests.test_golden_qwen2vl import IMAGE_TOKEN, VIDEO_TOKEN, VISION_START, _tiny_qwen2vl  # noqa: E402


def _gif(num_frames=4, size=(32, 24)):
    """Animated GIF whose frames differ (content must matter)."""
    from PIL import Image

    frames = []
    for i in range(num_frames):
        img = Image.new("RGB", size, ((i * 60) % 256, 30, (255 - i * 50) % 256))
        px = img.load()
        for x in range(size[0]):
            px[x, (x + i) % size[1]] = (255, 255, 0)
        frames.append(img)
    buf = io.BytesIO()
    frames[0].save(buf, format="GIF", save_all=True, append_images=frames[1:],
                   duration=100, loop=0)
    return buf.getvalue()


def test_golden_qwen2vl_video_logits(tmp_path):
    m = _tiny_qwen2vl()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    tcfg, vcfg, lm_params, vis_params = load_vlm(tmp_path, dtype="float32")

    # 4 frames -> temporal grid t=2 at temporal_patch_size 2.
    rng = np.random.default_rng(3)
    frames = rng.standard_normal((4, 3, 24, 32)).astype(np.float32) * 0.4
    patches, grid = patchify_frames(frames, TEST_TINY_QWEN2VL_VISION)
    assert grid[0] == 2
    n_vid = grid[0] * grid[1] * grid[2] // 4
    prompt = [3, VISION_START] + [VIDEO_TOKEN] * n_vid + [7, 42]
    t = len(prompt)

    with torch.no_grad():
        hf_logits = m(
            input_ids=torch.tensor([prompt]),
            pixel_values_videos=torch.tensor(patches),
            video_grid_thw=torch.tensor([list(grid)]),
        ).logits[0].float().numpy()

    mm = encode_qwen2vl(vis_params, vcfg, jnp.asarray(patches), grid)
    assert mm.shape == (n_vid, 64)
    pos3, _delta = mrope_position_ids(
        prompt, [grid], image_token_id=IMAGE_TOKEN, video_token_id=VIDEO_TOKEN,
    )
    # Temporal coordinate advances across the video's frame groups.
    vid_cols = pos3[0, 2 : 2 + n_vid]
    assert vid_cols.max() > vid_cols.min()

    page_size = 8
    k_cache, v_cache = llama.init_kv_cache(tcfg, num_pages=16, page_size=page_size)
    n_pages = -(-t // page_size)
    tables = jnp.asarray([list(range(1, 1 + n_pages))], jnp.int32)
    positions = jnp.arange(t, dtype=jnp.int32)[None]
    slots = jnp.take_along_axis(tables, positions // page_size, axis=1) * page_size + positions % page_size
    ours, _, _ = llama.forward(
        lm_params, tcfg, jnp.asarray([prompt], jnp.int32), positions,
        k_cache, v_cache, tables, slots, jnp.asarray([t - 1], jnp.int32),
        mm_embeds=mm[None], mrope_positions=jnp.asarray(pos3)[None],
    )
    np.testing.assert_allclose(np.asarray(ours)[0], hf_logits[t - 1], atol=2e-3, rtol=1e-3)


@pytest.mark.e2e
async def test_video_request_served_e2e_qwen2vl(tmp_path):
    """Served video_url request through the full stack: frame sampling ->
    temporal tower -> video placeholders -> M-RoPE prefill -> tokens."""
    import base64

    import aiohttp

    from dynamo_tpu.launch import run_local

    m = _tiny_qwen2vl()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    name = tmp_path.name
    url = "data:image/gif;base64," + base64.b64encode(_gif()).decode()

    handles = await run_local(str(tmp_path), port=0, num_pages=256, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        body = {
            "model": name,
            "messages": [{"role": "user", "content": [
                {"type": "text", "text": "what happens? "},
                {"type": "video_url", "video_url": {"url": url}},
            ]}],
            "max_tokens": 5, "temperature": 0,
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(base + "/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
        assert out["choices"][0]["message"]["content"]
        # Placeholder accounting: the video expanded to t*h*w/4 tokens under
        # the VIDEO token id, all covered by embeddings (engine would have
        # rejected a mismatch).
        from dynamo_tpu.encode import EncodeService
        enc = next(sv for sv in handles["services"] if isinstance(sv, EncodeService))
        assert enc.images_encoded == 1
        (grid,) = enc._encode_by_grid  # one video geometry compiled
        assert grid[0] >= 2  # real temporal extent
        assert out["usage"]["prompt_tokens"] > grid[0] * grid[1] * grid[2] // 4
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


@pytest.mark.e2e
async def test_video_request_served_e2e_llava(tmp_path):
    """LLaVA-class tower: a video becomes a sampled frame stack through the
    image tower; placeholders expand to frames * num_patches under the image
    token (the reference's video_prefill recipe)."""
    import base64

    import aiohttp

    from tests.test_golden_vision import _tiny_llava

    from dynamo_tpu.launch import run_local

    m = _tiny_llava()
    m.save_pretrained(str(tmp_path), safe_serialization=True)
    name = tmp_path.name
    url = "data:image/gif;base64," + base64.b64encode(_gif(num_frames=6)).decode()

    handles = await run_local(str(tmp_path), port=0, num_pages=256, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        body = {
            "model": name,
            "messages": [{"role": "user", "content": [
                {"type": "text", "text": "clip: "},
                {"type": "video_url", "video_url": {"url": url}},
            ]}],
            "max_tokens": 4, "temperature": 0,
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(base + "/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
        assert out["choices"][0]["message"]["content"]
        # 6 frames x 16 patches = 96 placeholders + text.
        assert out["usage"]["prompt_tokens"] > 96
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()


def test_extract_frames_sampling():
    from dynamo_tpu.models.vision import extract_frames

    frames = extract_frames(_gif(num_frames=10), 4)
    assert len(frames) == 4
    # A still PNG yields one frame.
    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", (8, 8), (1, 2, 3)).save(buf, format="PNG")
    assert len(extract_frames(buf.getvalue(), 4)) == 1
