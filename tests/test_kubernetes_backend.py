"""KubernetesBackend against a mocked k8s API server (VERDICT r3 item 6).

The mock implements the three verbs the backend uses — server-side apply
(PATCH application/apply-patch+yaml), labeled deletecollection, and list —
over an in-memory object store, so the full operator control loop runs:
GraphDeployment record -> reconcile -> objects materialized in the
"cluster"; planner DeploymentConnector scale -> re-reconcile -> Deployment
spec.replicas patched.
"""

import asyncio
import json

import pytest

aiohttp = pytest.importorskip("aiohttp")
from aiohttp import web  # noqa: E402

from dynamo_tpu.deploy.kubernetes import (  # noqa: E402
    DEPLOYMENT_LABEL,
    KubernetesBackend,
    ManifestError,
    validate_manifest,
)
from dynamo_tpu.deploy.objects import STORE_PREFIX, GraphDeployment  # noqa: E402


class MockApiServer:
    """Minimal k8s apiserver: namespaced objects in a dict."""

    def __init__(self) -> None:
        self.objects: dict[tuple[str, str, str], dict] = {}  # (plural, ns, name)
        self.patches = 0

    def _routes(self, app: web.Application) -> None:
        for prefix, plural in (
            ("/apis/apps/v1", "deployments"),
            ("/api/v1", "services"),
            ("/api/v1", "configmaps"),
        ):
            base = f"{prefix}/namespaces/{{ns}}/{plural}"
            app.router.add_patch(base + "/{name}", self._make_patch(plural))
            app.router.add_get(base, self._make_list(plural))
            app.router.add_delete(base, self._make_delete_collection(plural))

    def _make_patch(self, plural):
        async def handler(request: web.Request) -> web.Response:
            assert request.headers["Content-Type"] == "application/apply-patch+yaml"
            assert request.query.get("fieldManager"), "server-side apply needs fieldManager"
            doc = json.loads(await request.text())
            key = (plural, request.match_info["ns"], request.match_info["name"])
            created = key not in self.objects
            self.objects[key] = doc
            self.patches += 1
            return web.json_response(doc, status=201 if created else 200)

        return handler

    def _make_list(self, plural):
        async def handler(request: web.Request) -> web.Response:
            sel = request.query.get("labelSelector", "")
            items = [
                doc for (pl, ns, _n), doc in self.objects.items()
                if pl == plural and ns == request.match_info["ns"]
                and self._matches(doc, sel)
            ]
            return web.json_response({"items": items})

        return handler

    def _make_delete_collection(self, plural):
        async def handler(request: web.Request) -> web.Response:
            sel = request.query.get("labelSelector", "")
            doomed = [
                key for key, doc in self.objects.items()
                if key[0] == plural and key[1] == request.match_info["ns"]
                and self._matches(doc, sel)
            ]
            for key in doomed:
                del self.objects[key]
            return web.json_response({"deleted": len(doomed)})

        return handler

    @staticmethod
    def _matches(doc: dict, selector: str) -> bool:
        if not selector:
            return True
        labels = doc.get("metadata", {}).get("labels", {})
        for clause in selector.split(","):
            k, _, v = clause.partition("=")
            if labels.get(k) != v:
                return False
        return True


import contextlib


@contextlib.asynccontextmanager
async def mock_cluster():
    """(server, base_url) — the repo's test runner has no async fixtures."""
    server = MockApiServer()
    app = web.Application()
    server._routes(app)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    try:
        yield server, f"http://127.0.0.1:{port}"
    finally:
        await runner.cleanup()


def _dep(name="demo", replicas=2):
    return GraphDeployment(
        name=name, graph="dynamo_tpu.sdk.graphs:Frontend",
        config={"Worker": {"replicas": replicas, "mock": True}},
        generation=1,
    )


async def test_apply_materializes_objects_and_delete_clears_them():
    async with mock_cluster() as (server, url):
        backend = KubernetesBackend(url, namespace="prod")
        try:
            counts = await backend.apply(_dep(replicas=3))
            assert counts.get("Worker") == 3
            kinds = {k[0] for k in server.objects}
            assert kinds == {"deployments", "services", "configmaps"}
            # Every object is namespaced where asked and labeled for deletion.
            for (plural, ns, _name), doc in server.objects.items():
                assert ns == "prod"
                assert doc["metadata"]["labels"][DEPLOYMENT_LABEL] == "demo"
            live = await backend.replicas("demo")
            assert live.get("Worker") == 3

            await backend.delete("demo")
            assert not server.objects, "labeled deletecollection left objects behind"
        finally:
            await backend.close()


async def test_reapply_scales_replicas():
    """Spec change -> server-side re-apply patches spec.replicas."""
    async with mock_cluster() as (server, url):
        backend = KubernetesBackend(url)
        try:
            await backend.apply(_dep(replicas=1))
            assert (await backend.replicas("demo")).get("Worker") == 1
            await backend.apply(_dep(replicas=4))
            assert (await backend.replicas("demo")).get("Worker") == 4
        finally:
            await backend.close()


async def test_operator_with_k8s_backend_and_planner_scale():
    """Full control loop: store record -> Operator(reconcile) -> k8s objects;
    planner DeploymentConnector scale -> reconcile -> replicas patched."""
    from dynamo_tpu.deploy.operator import Operator
    from dynamo_tpu.planner.connector import DeploymentConnector
    from dynamo_tpu.planner.core import PlanDecision
    from dynamo_tpu.runtime.discovery import MemoryStore

    async with mock_cluster() as (server, url):
        store = MemoryStore()
        backend = KubernetesBackend(url)
        op = Operator(store, backend, resync_seconds=3600)
        try:
            dep = _dep(replicas=2)
            await store.put(dep.key, dep.to_bytes())
            await op.start()
            for _ in range(100):
                if (await backend.replicas("demo")).get("Worker") == 2:
                    break
                await asyncio.sleep(0.05)
            assert (await backend.replicas("demo")).get("Worker") == 2

            connector = DeploymentConnector(store, "demo", decode_service="Worker")
            await connector.apply(PlanDecision(decode_workers=5, prefill_workers=0, predicted_prefill_tps=0.0, predicted_decode_tps=0.0))
            assert connector.scale_events == 1
            for _ in range(100):
                if (await backend.replicas("demo")).get("Worker") == 5:
                    break
                await asyncio.sleep(0.05)
            assert (await backend.replicas("demo")).get("Worker") == 5

            # Status written back to the record.
            rec = GraphDeployment.from_bytes(await store.get(STORE_PREFIX + "demo"))
            from dynamo_tpu.deploy.objects import DeploymentPhase
            assert rec.phase == DeploymentPhase.RUNNING.value and rec.services_ready.get("Worker") == 5
        finally:
            await op.close()


def test_validate_manifest_rejects_bad_shapes():
    good = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "ok-name", "labels": {DEPLOYMENT_LABEL: "d"}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": "x"}},
            "template": {
                "metadata": {"labels": {"app": "x"}},
                "spec": {"containers": [{"name": "c", "image": "img"}]},
            },
        },
    }
    validate_manifest(good)

    import copy

    bad_name = copy.deepcopy(good)
    bad_name["metadata"]["name"] = "Bad_Name"
    with pytest.raises(ManifestError, match="DNS-1123"):
        validate_manifest(bad_name)

    bad_sel = copy.deepcopy(good)
    bad_sel["spec"]["template"]["metadata"]["labels"] = {"app": "y"}
    with pytest.raises(ManifestError, match="selector"):
        validate_manifest(bad_sel)

    no_label = copy.deepcopy(good)
    del no_label["metadata"]["labels"]
    with pytest.raises(ManifestError, match="label"):
        validate_manifest(no_label)

    no_img = copy.deepcopy(good)
    del no_img["spec"]["template"]["spec"]["containers"][0]["image"]
    with pytest.raises(ManifestError, match="image"):
        validate_manifest(no_img)


async def test_rendered_bundle_passes_validation():
    """Everything the renderer emits must pre-flight clean."""
    from dynamo_tpu.deploy.manifests import render_deployment
    from dynamo_tpu.sdk.graph import load_graph

    dep = _dep()
    for doc in render_deployment(dep, load_graph(dep.graph)):
        validate_manifest(doc)
