"""MLA (DeepSeek latent attention): absorbed-vs-naive equivalence, paged
prefill/decode consistency, cache sizing, engine + HTTP integration.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.models.mla import init_mla_params, mla_attention, mla_attention_naive
from dynamo_tpu.ops.rope import rope_frequencies

CFG = PRESETS["test-tiny-mla"]


def _layer_params(seed=0):
    stacked = init_mla_params(CFG, jax.random.PRNGKey(seed), jnp.float32, 1)
    return jax.tree.map(lambda x: x[0], stacked)


def test_absorbed_matches_naive():
    lp = _layer_params()
    rng = np.random.default_rng(0)
    B, T, PS, PAGES = 2, 12, 4, 8
    h = jnp.asarray(rng.standard_normal((B, T, CFG.hidden_size)), jnp.float32) * 0.3
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
    inv_freq = jnp.asarray(rope_frequencies(CFG.qk_rope_head_dim, theta=CFG.rope_theta))

    want = mla_attention_naive(lp, CFG, h, positions, inv_freq)

    c_cache = jnp.zeros((PAGES, PS, CFG.kv_lora_rank), jnp.float32)
    r_cache = jnp.zeros((PAGES, PS, CFG.qk_rope_head_dim), jnp.float32)
    # seq 0 -> pages 1..3, seq 1 -> pages 4..6 (page 0 = null)
    tables = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    slots = tables[:, :, None] * PS + jnp.arange(PS)[None, None, :]
    slots = slots.reshape(B, -1)[:, :T]
    got, _, _ = mla_attention(lp, CFG, h, positions, c_cache, r_cache, tables, slots, inv_freq)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4)


def test_paged_decode_matches_prefill():
    """Prefill all-at-once vs prefill + one-token decode steps: same logits."""
    cfg = CFG
    params = llama.init_params(cfg, 1)
    PAGES, PS = 8, 4
    T = 10
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, T)), jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None]
    tables = jnp.asarray([[1, 2, 3]], jnp.int32)
    slots_full = (tables[:, :, None] * PS + jnp.arange(PS)[None, None, :]).reshape(1, -1)[:, :T]
    last = jnp.asarray([T - 1], jnp.int32)

    kc, vc = llama.init_kv_cache(cfg, PAGES, PS)
    logits_full, _, _ = llama.forward(
        params, cfg, tokens, positions, kc, vc, tables, slots_full, last
    )

    # incremental: prefill T-1 then decode the last token
    kc2, vc2 = llama.init_kv_cache(cfg, PAGES, PS)
    _, kc2, vc2 = llama.forward(
        params, cfg, tokens[:, : T - 1], positions[:, : T - 1], kc2, vc2,
        tables, slots_full[:, : T - 1], jnp.asarray([T - 2], jnp.int32),
    )
    logits_step, _, _ = llama.forward(
        params, cfg, tokens[:, T - 1 :], positions[:, T - 1 :], kc2, vc2,
        tables, slots_full[:, T - 1 :], jnp.asarray([0], jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(logits_full), np.asarray(logits_step), atol=2e-3, rtol=2e-3
    )


def test_mla_cache_is_small():
    v3 = PRESETS["deepseek-v3-ep"]
    assert v3.attn_type == "mla"
    # latent(512) + lane-padded rope(128) per token per layer vs the GQA
    # stand-in (rope stream padded to one 128-lane tile for Mosaic DMA).
    assert v3.kv_bytes_per_token() == v3.num_layers * (512 + 128) * 2
    gqa_equiv = 2 * v3.num_layers * v3.kv_dim * 2
    assert v3.kv_bytes_per_token() * 25 < gqa_equiv  # still ~25x smaller

    kc, vc = llama.init_kv_cache(CFG, 4, 4)
    assert kc.shape == (CFG.num_layers, 4, 4, CFG.kv_lora_rank)
    assert vc.shape == (CFG.num_layers, 4, 4, max(CFG.qk_rope_head_dim, 128))


def test_mla_forward_on_tp_mesh():
    """MLA under GSPMD: tp-sharded heads produce single-device logits."""
    from dynamo_tpu.parallel.mesh import MeshPlan, make_mesh
    from dynamo_tpu.parallel.sharding import param_shardings

    cfg = CFG
    params = llama.init_params(cfg, 5)
    logits_ref = _tiny_forward(params, cfg)

    mesh = make_mesh(MeshPlan(tp=4))
    sh = param_shardings(mesh, params)
    placed = jax.tree.map(lambda x, s: jax.device_put(x, s), params, sh)
    logits_tp = _tiny_forward(placed, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_ref), np.asarray(logits_tp), atol=2e-3, rtol=2e-3
    )


def _tiny_forward(params, cfg):
    PAGES, PS, T = 8, 4, 8
    tokens = jnp.arange(T, dtype=jnp.int32)[None] % cfg.vocab_size
    positions = jnp.arange(T, dtype=jnp.int32)[None]
    tables = jnp.asarray([[1, 2]], jnp.int32)
    slots = (tables[:, :, None] * PS + jnp.arange(PS)[None, None, :]).reshape(1, -1)[:, :T]
    kc, vc = llama.init_kv_cache(cfg, PAGES, PS)
    logits, _, _ = llama.forward(
        params, cfg, tokens, positions, kc, vc, tables, slots,
        jnp.asarray([T - 1], jnp.int32),
    )
    return logits


def test_mla_checkpoint_roundtrip(tmp_path):
    """params -> HF deepseek_v3 checkpoint (kv_b_proj packing) -> params."""
    from dynamo_tpu.models.loader import load_model, save_params

    params = llama.init_params(CFG, 7)
    save_params(tmp_path, CFG, params)
    cfg2, loaded = load_model(tmp_path, name=CFG.name, dtype=CFG.dtype)
    assert cfg2.attn_type == "mla"
    assert cfg2.kv_lora_rank == CFG.kv_lora_rank
    assert cfg2.q_lora_rank == CFG.q_lora_rank
    assert cfg2.qk_rope_head_dim == CFG.qk_rope_head_dim

    flat_a = jax.tree.leaves(jax.tree.map(np.asarray, params))
    flat_b = jax.tree.leaves(jax.tree.map(np.asarray, loaded))
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), atol=0, rtol=0)


async def test_mla_serving_end_to_end():
    import aiohttp

    from dynamo_tpu.launch import run_local

    handles = await run_local("test-tiny-mla", port=0, num_pages=64, max_batch_size=4)
    try:
        async with aiohttp.ClientSession() as s:
            r = await s.post(
                f"http://127.0.0.1:{handles['port']}/v1/completions",
                json={"model": "test-tiny-mla", "prompt": "hello", "max_tokens": 6},
            )
            doc = await r.json()
            assert r.status == 200, doc
            assert doc["usage"]["completion_tokens"] == 6
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
