"""Two-OS-process KV pull: real descriptor exchange over the runtime
transport (VERDICT r3 item 3b).

The sender (this process) prefills a prompt through a real engine core,
then runs the full ``send_pull_offer`` protocol against a receiver engine
living in a SEPARATE OS process (tests/_pull_child.py) over a real TCP
runtime transport:

- "wire" mode: phase-1 miss negotiation, then a phase-2 pull whose bytes
  cross the process boundary over the socket wire (tests/_pull_wire.py —
  same contract as the PJRT transfer engine, which CPU lacks); injected
  page content is read back from the child and compared bit-for-bit.
- "unsupported" mode: the child's capability probe says no, the sender
  must get ``None`` back (no gather, no offer) and the packed-bytes
  fallback must inject the chain — the fallback negotiation end to end.

The real ``jax.experimental.transfer`` wire has NOT been exercised on any
available hardware: the axon-tunneled v5e's PJRT plugin does not implement
the transfer-engine API. ``bench.py``'s kv_pull probe attempts it on every
hardware run and records the fallback
(``"transfer_engine": "unsupported_on_this_plugin"`` in BENCH_r04) — the
hardware numbers there are in-process page gathers plus the cross-process
packed-bytes TCP wire, not a device-path pull.
"""

import asyncio
import os
import subprocess
import sys

import numpy as np
import pytest

from dynamo_tpu.disagg.pull_transport import set_transport
from dynamo_tpu.disagg.transfer import (
    collect_prefill_blocks,
    send_blocks,
    send_pull_offer,
)
from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.tcp import TcpTransport
from dynamo_tpu.tokens import compute_block_hashes

from _pull_wire import SocketWireTransport

CHILD = os.path.join(os.path.dirname(__file__), "_pull_child.py")
PAGE = 4
PROMPT = [(i * 7 + 3) % 64 for i in range(32)]  # 8 full pages


def _spawn_child(mode: str) -> tuple[subprocess.Popen, str, str]:
    proc = subprocess.Popen(
        [sys.executable, CHILD, mode],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    for line in proc.stdout:
        if line.startswith("ADDR "):
            _tag, kv_addr, read_addr = line.split()
            return proc, kv_addr, read_addr
    raise RuntimeError(f"child exited without ADDR (rc={proc.wait()})")


def _stop_child(proc: subprocess.Popen) -> None:
    try:
        proc.stdin.close()
        proc.wait(timeout=20)
    except Exception:
        proc.kill()


def _sender_core() -> EngineCore:
    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    runner = ModelRunner(
        cfg, params, num_pages=32, page_size=PAGE, max_batch_size=4,
        prefill_bucket=16, attn_impl="reference",
    )
    core = EngineCore(runner, EngineConfig(
        num_pages=32, page_size=PAGE, max_batch_size=4,
        max_prefill_tokens=128, max_seq_len=128,
    ))
    # A real 1-token generation commits the prompt's full pages — the same
    # thing the prefill worker does before shipping KV.
    core.add_request(PreprocessedRequest(
        token_ids=list(PROMPT), sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=1, ignore_eos=True), request_id="warm",
    ), Context())
    for _ in range(50):
        if not core.has_work:
            break
        core.step()
    return core


async def _read_child_pages(transport, read_addr, hashes) -> dict:
    out = {}
    async for item in transport.generate(read_addr, {"hashes": hashes}, Context()):
        out = item
    return out


@pytest.mark.e2e
async def test_two_process_pull_wire():
    wire = SocketWireTransport()
    set_transport(wire, supported=True)
    proc, kv_addr, read_addr = _spawn_child("wire")
    transport = TcpTransport(host="127.0.0.1")
    try:
        core = _sender_core()
        hashes = compute_block_hashes(PROMPT, PAGE, salt=core.config.salt)
        assert len(hashes) == 8

        result = await send_pull_offer(transport, kv_addr, "req-1", core, hashes)
        assert result is not None and result["injected"] == len(hashes), result
        assert wire.served >= 1, "the offer was never pulled over the socket wire"
        assert not wire.offers, "offer not released after completion"

        # Bit-for-bit content check: the child's committed pages must equal
        # the sender's source pages.
        child = await _read_child_pages(transport, read_addr, hashes)
        assert child["n"] == len(hashes)
        src_pages = core.allocator.match_prefix(hashes)
        try:
            src = core.runner.read_pages(src_pages)
        finally:
            core.allocator.release(src_pages)
        for i, (k, v) in enumerate(src):
            assert child["k"][i] == np.ascontiguousarray(k).tobytes(), f"page {i} K mismatch"
            assert child["v"][i] == np.ascontiguousarray(v).tobytes(), f"page {i} V mismatch"

        # Warm-cache re-offer: the child already has the chain, so phase 1
        # completes it — no new gather/offer (the ADVICE r3 leak class).
        offered_before = wire.offered
        result2 = await send_pull_offer(transport, kv_addr, "req-2", core, hashes)
        assert result2 is not None and result2["injected"] == len(hashes)
        assert wire.offered == offered_before
    finally:
        _stop_child(proc)
        await transport.close()
        set_transport(None, None)
        wire.close()


@pytest.mark.e2e
async def test_two_process_fallback_negotiation():
    """Receiver without transfer-engine support: the sender's phase-1 query
    must come back pull_unsupported (send_pull_offer -> None, nothing
    offered) and the packed-bytes stream must deliver the chain."""
    wire = SocketWireTransport()
    set_transport(wire, supported=True)  # sender side WOULD do pulls
    proc, kv_addr, read_addr = _spawn_child("unsupported")
    transport = TcpTransport(host="127.0.0.1")
    try:
        core = _sender_core()
        hashes = compute_block_hashes(PROMPT, PAGE, salt=core.config.salt)

        result = await send_pull_offer(transport, kv_addr, "req-1", core, hashes)
        assert result is None
        assert wire.offered == 0, "sender gathered/offered despite unsupported receiver"

        blocks = collect_prefill_blocks(core, hashes)
        assert len(blocks) == len(hashes)
        summary = await send_blocks(transport, kv_addr, "req-1", blocks)
        assert summary["injected"] == len(hashes), summary

        child = await _read_child_pages(transport, read_addr, hashes)
        assert child["n"] == len(hashes)
    finally:
        _stop_child(proc)
        await transport.close()
        set_transport(None, None)
        wire.close()
