"""Cache-aware serving (ISSUE 12): residual-prefill-cost admission pricing,
the router's cache-aware cost term, env gating, and the engine seam that
wires the admission pricing hook only when ``DYN_CACHE_AWARE`` is on."""

from collections import deque

import pytest

from dynamo_tpu.engine.sequence import Sequence
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.sched import (
    AdmissionConfig,
    AdmissionController,
    TenantQuota,
    TenantRegistry,
    TtftPredictor,
    cache_aware_enabled,
    configure_cache_aware,
)


def _req(tokens, *, tenant=None, priority=0, max_tokens=4):
    return PreprocessedRequest(
        token_ids=list(tokens),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        tenant_id=tenant,
        priority=priority,
    )


def _seq(seq_id, n_tokens, *, arrival, tenant=None, priority=0):
    seq = Sequence.from_request(
        seq_id, _req(range(1, n_tokens + 1), tenant=tenant, priority=priority),
        Context(), page_size=16, salt=0,
    )
    seq.arrival_time = arrival
    return seq


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _controller(clk, *, cached_fn=None, quota=None):
    tenants = TenantRegistry(clock=clk)
    if quota:
        for tenant, q in quota.items():
            tenants.configure(tenant, q)
    ctl = AdmissionController(
        AdmissionConfig(ttft_budget_s=0.5, tier_stretch=2.0),
        predictor=TtftPredictor(), tenants=tenants, clock=clk,
    )
    ctl.cached_tokens_fn = cached_fn
    return ctl


# -- residual-cost admission --------------------------------------------------


def test_residual_pricing_admits_cached_long_before_cold_short():
    """The acceptance scenario: a 95%-cached 3000-token prompt from a
    quota-bounded tenant is admitted AHEAD of a cold 300-token prompt.
    Cache-blind pricing charges the full prompt, fails the tenant's
    in-flight cap, and defers the long request behind the cold one."""
    cached = {0: 2850, 1: 0}  # seq 0: 95% of 3000 tokens already resident

    def scenario(priced):
        clk = _Clock(t=2.0)
        ctl = _controller(
            clk,
            cached_fn=(lambda s: cached[s.seq_id]) if priced else None,
            quota={"bulk": TenantQuota(max_inflight_tokens=600)},
        )
        ctl.tenants.on_admit("bulk", 400)  # tenant already has work in flight
        long = _seq(0, 3000, arrival=0.0, tenant="bulk")
        cold = _seq(1, 300, arrival=0.2)
        waiting = deque([cold, long])  # cold ahead in raw arrival-queue order
        admissible = ctl.prepare(waiting, running=0, slots=8)
        return admissible, [s.seq_id for s in waiting]

    # Residual pricing: the long prompt charges 3000-2850=150 tokens, fits
    # the 600 cap (400+150), and its earlier arrival gives it less slack.
    admissible, order = scenario(priced=True)
    assert admissible == 2
    assert order == [0, 1]
    # Cache-blind: 400+3000 > 600 defers it behind the admissible cold one.
    admissible, order = scenario(priced=False)
    assert admissible == 1
    assert order == [1, 0]


def test_on_admit_charges_residual_and_refunds_same_amount():
    clk = _Clock()
    ctl = _controller(clk, cached_fn=lambda s: 2850)
    seq = _seq(7, 3000, arrival=0.0, tenant="acme")
    ctl.on_admit(seq)
    assert ctl._charges[7] == ("acme", 150)
    assert ctl.tenants.inflight("acme") == 150
    ctl.on_finish(seq)
    assert ctl.tenants.inflight("acme") == 0
    # Over-estimate clamps: at least the final token is always charged.
    ctl.cached_tokens_fn = lambda s: 10**9
    tiny = _seq(8, 4, arrival=0.0, tenant="acme")
    ctl.on_admit(tiny)
    assert ctl._charges[8] == ("acme", 1)


def test_estimate_failure_degrades_to_cache_blind():
    def boom(seq):
        raise RuntimeError("indexer down")

    clk = _Clock()
    ctl = _controller(clk, cached_fn=boom)
    seq = _seq(3, 40, arrival=0.0, tenant="t")
    waiting = deque([seq])
    assert ctl.prepare(waiting, running=0, slots=8) == 1
    ctl.on_admit(seq)
    assert ctl._charges[3] == ("t", 40)  # full cache-blind charge


# -- router cache-aware cost term ---------------------------------------------


def test_router_cache_term_prefers_overlap_worker_stale_falls_back():
    from dynamo_tpu.router.indexer import OverlapScores
    from dynamo_tpu.router.scheduler import KvScheduler, SchedulerConfig

    overlaps = OverlapScores(scores={2: 8})  # worker 2 holds 8 of 10 blocks
    # overlap_weight=0 isolates the new term: base costs tie exactly.
    base = KvScheduler(SchedulerConfig(overlap_weight=0.0))
    costs = base.costs(10, overlaps, {}, [1, 2])
    assert costs[1] == pytest.approx(costs[2])
    assert base.select(costs) == 1  # existing tie-break: lowest id

    armed = KvScheduler(SchedulerConfig(
        overlap_weight=0.0, cache_aware_weight=1.0, cache_block_tokens=16,
        cache_rate_tokens_per_s=20000.0, cache_max_staleness_s=5.0,
        ttft_slo_s=0.5,
    ))
    costs = armed.costs(10, overlaps, {}, [1, 2])
    assert costs[2] < costs[1]
    assert armed.select(costs) == 2  # prefix-overlap worker wins
    # Residual seconds normalized by the budget: (blocks*16/20000)/0.5.
    assert costs[1] - costs[2] == pytest.approx((8 * 16 / 20000.0) / 0.5)

    # The overlap worker's KV-event feed goes stale: it is priced as cold,
    # the term ties, and selection falls back to the existing ordering.
    costs = armed.costs(10, overlaps, {}, [1, 2], staleness={1: 0.0, 2: 99.0})
    assert costs[1] == pytest.approx(costs[2])
    assert armed.select(costs) == 1
    # Every worker stale -> constant term -> same fallback.
    costs = armed.costs(10, overlaps, {}, [1, 2], staleness={1: 99.0, 2: 99.0})
    assert costs[1] == pytest.approx(costs[2])
    assert armed.select(costs) == 1


def test_configure_cache_aware_gated_on_master_toggle(monkeypatch):
    from dynamo_tpu.router.scheduler import SchedulerConfig

    cfg = SchedulerConfig()
    monkeypatch.delenv("DYN_CACHE_AWARE", raising=False)
    assert not cache_aware_enabled()
    configure_cache_aware(cfg, block_tokens=32)
    assert cfg.cache_aware_weight == 0.0  # off: untouched (bit-identical cost)
    monkeypatch.setenv("DYN_CACHE_AWARE", "1")
    monkeypatch.setenv("DYN_CACHE_AWARE_WEIGHT", "2.5")
    monkeypatch.setenv("DYN_CACHE_AWARE_RATE_TOKENS_PER_S", "40000")
    monkeypatch.setenv("DYN_CACHE_AWARE_MAX_STALENESS_S", "3")
    assert cache_aware_enabled()
    configure_cache_aware(cfg, block_tokens=32)
    assert cfg.cache_aware_weight == 2.5
    assert cfg.cache_rate_tokens_per_s == 40000.0
    assert cfg.cache_max_staleness_s == 3.0
    assert cfg.cache_block_tokens == 32


# -- engine seam --------------------------------------------------------------


def _mock_core(admission=None, **cfg_kw):
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.mocker import MockRunner

    kw = dict(
        num_pages=256, page_size=16, max_batch_size=8,
        max_prefill_tokens=4096, max_seq_len=8192,
        enable_prefix_caching=True, chunk_prefill_tokens=64,
    )
    kw.update(cfg_kw)
    cfg = EngineConfig(**kw)
    runner = MockRunner(num_pages=cfg.num_pages, page_size=cfg.page_size, realtime=False)
    return EngineCore(runner, cfg, admission=admission)


def test_engine_wires_pricing_hook_only_when_cache_aware():
    ctl = AdmissionController(predictor=TtftPredictor(), tenants=TenantRegistry())
    core = _mock_core(admission=ctl, cache_aware=False)
    assert core.admission.cached_tokens_fn is None  # off: cache-blind pricing
    ctl2 = AdmissionController(predictor=TtftPredictor(), tenants=TenantRegistry())
    core2 = _mock_core(admission=ctl2, cache_aware=True)
    assert core2.admission.cached_tokens_fn is not None


def test_cached_prefix_tokens_counts_resident_g1_match():
    """After a request finishes, an identical waiting prompt prices almost
    fully cached (capped at len-1: the final token always computes)."""
    core = _mock_core(cache_aware=True)
    seq = core.add_request(_req(range(1, 65), max_tokens=2))
    for _ in range(50):
        if not core.has_work:
            break
        core.step()
    probe = core.add_request(_req(range(1, 65)))  # identical prompt, waiting
    est = core._cached_prefix_tokens(probe)
    assert est >= 48  # at least the full pages of the shared prefix
    assert est <= 63  # never the whole prompt
    # Different prompt: nothing resident.
    other = core.add_request(_req(range(1000, 1064)))
    assert core._cached_prefix_tokens(other) == 0
