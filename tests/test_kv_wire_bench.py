"""Cross-process KV-wire probe (bench module): two real OS processes over a
real TCP socket, tiny geometry. The hardware run (chip-side sender) uses the
same code path via bench.py's probe_cross_process_wire."""

import sys

import pytest

from dynamo_tpu.bench.kv_wire import (
    measure_cross_process,
    sweep_cross_process,
    wire_config,
)


@pytest.mark.e2e
async def test_cross_process_wire_measures(tmp_path):
    cfg = wire_config(num_layers=2, num_kv_heads=2, head_dim=16)
    out = await measure_cross_process(
        pages_per_chain=2, iters=3, cfg=cfg, page_size=16,
        child_cmd=[
            sys.executable, "-m", "dynamo_tpu.bench.kv_wire",
            "2", "2", "16", "16", str(2 * 3 + 4), str(2 * 16),
        ],
    )
    assert out["wire"] == "tcp_cross_process"
    assert out["iters"] == 3 and len(out["per_iter"]) == 3
    assert out["chunk_pages"] == 1  # 2 pages -> 2 chunks: the pipeline engages
    # Default wire is v3 striped (2 chunks cap the stripes at 2).
    assert out["protocol"] == "v3"
    assert out["streams"] == 2
    # Exact payload geometry: every transfer moved the full chain's bytes —
    # L(2) * ps(16) * kv_heads(2) * hd(16) * 2B, K and V, 2 pages per chain.
    page_bytes = 2 * 16 * 2 * 16 * 2 * 2
    for it in out["per_iter"]:
        assert it["bytes"] == 2 * page_bytes
        assert it["total_s"] > 0
        # The stream reports every pipeline phase per iteration.
        for phase in ("gather_s", "pack_s", "wire_s", "scatter_s"):
            assert it[phase] >= 0
        assert it["gather_s"] + it["pack_s"] + it["wire_s"] > 0
        # overlap_s = sum(phases) - total_s; it exists (may be ~0 at this
        # tiny geometry where a chunk's DMA finishes before the wire does).
        assert "overlap_s" in it
    assert out["cold_gbytes_per_sec"] > 0
    assert out["amortized_gbytes_per_sec"] > 0
    assert out["amortized_wire_only_gbytes_per_sec"] >= out["amortized_gbytes_per_sec"]
    assert 0.0 <= out["overlap_frac"] <= 1.0


@pytest.mark.e2e
async def test_cross_process_wire_streams_zero_pins_v2(tmp_path):
    cfg = wire_config(num_layers=2, num_kv_heads=2, head_dim=16)
    out = await measure_cross_process(
        pages_per_chain=2, iters=2, cfg=cfg, page_size=16, streams=0,
        child_cmd=[
            sys.executable, "-m", "dynamo_tpu.bench.kv_wire",
            "2", "2", "16", "16", str(2 * 2 + 4), str(2 * 16),
        ],
    )
    assert out["protocol"] == "v2"
    assert out["streams"] == 0
    assert out["amortized_gbytes_per_sec"] > 0


@pytest.mark.e2e
@pytest.mark.slow
async def test_cross_process_wire_sweep(tmp_path):
    """The grid probe's contract: one combo per (streams, chunk) cell, the v2
    baseline present, and the headline keys bench.py promotes to the stable
    top level. Speedup magnitude is a real-geometry claim (bench/results),
    not asserted at this tiny size."""
    cfg = wire_config(num_layers=2, num_kv_heads=2, head_dim=16)
    out = await sweep_cross_process(
        pages_per_chain=2, iters=2, cfg=cfg, page_size=16,
        stream_counts=(0, 2), chunk_pages_list=(1,),
        child_cmd=[
            sys.executable, "-m", "dynamo_tpu.bench.kv_wire",
            "2", "2", "16", "16", str(2 * 2 + 4), str(2 * 16),
        ],
    )
    assert out["wire"] == "tcp_cross_process_sweep"
    assert len(out["sweep"]) == 2
    protos = {c["protocol"] for c in out["sweep"]}
    assert protos == {"v2", "v3"}
    assert out["v2_baseline"] is not None
    assert out["kv_wire_gbps"] > 0
    assert 0.0 <= out["kv_wire_overlap_frac"] <= 1.0
    assert out["speedup_vs_v2"] > 0
