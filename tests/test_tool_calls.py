"""Tool-call parsing unit tests + aggregation integration.

Parity: reference `preprocessor/tools/*` response parsing lifted to
OpenAI `message.tool_calls` shape."""

import asyncio
import json

from dynamo_tpu.frontend.tool_calls import parse_tool_calls


def test_hermes_style_single_call():
    text = 'Let me check.\n<tool_call>\n{"name": "get_weather", "arguments": {"city": "Paris"}}\n</tool_call>'
    content, calls = parse_tool_calls(text)
    assert content == "Let me check."
    assert len(calls) == 1
    c = calls[0]
    assert c["type"] == "function" and c["function"]["name"] == "get_weather"
    assert json.loads(c["function"]["arguments"]) == {"city": "Paris"}
    assert c["id"].startswith("call_")


def test_hermes_style_multiple_calls():
    text = (
        '<tool_call>{"name": "a", "arguments": {}}</tool_call>'
        '<tool_call>{"name": "b", "arguments": {"x": 1}}</tool_call>'
    )
    content, calls = parse_tool_calls(text)
    assert content == ""
    assert [c["function"]["name"] for c in calls] == ["a", "b"]


def test_llama3_bare_json_call():
    text = '{"name": "search", "parameters": {"query": "tpu"}}'
    content, calls = parse_tool_calls(text)
    assert content == ""
    assert calls[0]["function"]["name"] == "search"
    assert json.loads(calls[0]["function"]["arguments"]) == {"query": "tpu"}


def test_plain_text_untouched():
    for text in ("just a normal answer", '{"not_a_call": true}', "<tool_call>broken json</tool_call>"):
        content, calls = parse_tool_calls(text)
        assert calls == []
        assert content == text


def test_aggregate_chat_lifts_tool_calls():
    from dynamo_tpu.frontend.openai_format import aggregate_chat
    from dynamo_tpu.protocols.common import BackendOutput, FinishReason

    async def stream():
        yield BackendOutput(text='<tool_call>{"name": "f", "arguments": {"k": 2}}')
        yield BackendOutput(text="</tool_call>", finish_reason=FinishReason.STOP,
                            cumulative_tokens=12, prompt_tokens=5)

    async def run(parse):
        return await aggregate_chat("m", stream(), parse_tools=parse)

    out = asyncio.run(run(True))
    choice = out["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    assert choice["message"]["tool_calls"][0]["function"]["name"] == "f"
    assert choice["message"]["content"] is None

    out2 = asyncio.run(run(False))  # no tools declared: text passes through
    assert out2["choices"][0]["finish_reason"] == "stop"
    assert "tool_call" in out2["choices"][0]["message"]["content"]


def test_template_receives_tools():
    from dynamo_tpu.preprocessor import PromptFormatter

    tmpl = (
        "{% for m in messages %}{{ m.content }}{% endfor %}"
        "{% if tools %}TOOLS:{{ tools | length }}{% endif %}"
    )
    f = PromptFormatter(tmpl)
    out = f.render([{"role": "user", "content": "hi"}], tools=[{"type": "function"}])
    assert out.endswith("TOOLS:1")


def test_stream_jail_releases_plain_text():
    from dynamo_tpu.frontend.tool_calls import ToolCallStreamJail

    j = ToolCallStreamJail()
    got = "".join(j.push(c) for c in ["Hello ", "wor", "ld!"])
    trailing, calls = j.finish()
    assert got + trailing == "Hello world!"
    assert calls == []


def test_stream_jail_holds_marker_and_parses():
    from dynamo_tpu.frontend.tool_calls import ToolCallStreamJail

    j = ToolCallStreamJail()
    pieces = ["Sure. <tool", '_call>{"name": "f", ', '"arguments": {}}</tool_call>']
    got = "".join(j.push(p) for p in pieces)
    assert "tool_call" not in got  # markup never leaked
    assert got.startswith("Sure.")
    trailing, calls = j.finish()
    assert calls and calls[0]["function"]["name"] == "f"


def test_stream_jail_bare_json_buffered():
    from dynamo_tpu.frontend.tool_calls import ToolCallStreamJail

    j = ToolCallStreamJail()
    assert j.push('{"name": "g", ') == ""
    assert j.push('"parameters": {"a": 1}}') == ""
    trailing, calls = j.finish()
    assert calls[0]["function"]["name"] == "g"
    assert trailing == ""


def test_stream_jail_false_positive_flushes_as_text():
    from dynamo_tpu.frontend.tool_calls import ToolCallStreamJail

    j = ToolCallStreamJail()
    out = j.push("answer is <tool_call>not json")
    trailing, calls = j.finish()
    assert calls == []
    assert out + trailing == "answer is <tool_call>not json"
