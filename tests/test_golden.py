"""Golden-logit parity against HF transformers modeling code.

The strongest correctness evidence short of serving a real checkpoint
(VERDICT r2 item 5): build a tiny *seeded* HF model per family, save a real
HF checkpoint (config.json + safetensors), load it through this repo's
loader, and assert the paged-cache forward reproduces HF's logits — both
the prefill-phase logits and a decode step. This exercises, end to end:
weight-name mapping, transposition, rope conventions (incl. the DeepSeek
interleave fix), GQA/bias/MoE/MLA math, and cache write/read paths.

Reference parity target: the reference's real-model content asserts
(`tests/serve/test_dynamo_serve.py:94-317`) — here at logit granularity,
which is stricter and needs no network.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.config import ModelConfig  # noqa: E402
from dynamo_tpu.models.loader import load_params  # noqa: E402

PROMPT = [3, 17, 42, 99, 7, 123, 200, 5]


def _hf_logits(model, extra: list[int] | None = None) -> np.ndarray:
    ids = torch.tensor([PROMPT + (extra or [])])
    with torch.no_grad():
        return model(ids).logits[0].float().numpy()  # [T, vocab]


def _save(model, tmp_path):
    model = model.eval().float()
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    return model


def _our_forward(tmp_path, *, extra: list[int] | None = None):
    """Load the checkpoint and run prefill (+ optional decode steps for
    ``extra`` tokens) on a paged cache; returns logits after each step."""
    cfg = ModelConfig.from_hf(tmp_path / "config.json")
    params = load_params(tmp_path, cfg, dtype="float32")
    page_size = 8
    k_cache, v_cache = llama.init_kv_cache(cfg, num_pages=6, page_size=page_size)
    tables = jnp.asarray([[1, 2, 3, 4]], jnp.int32)

    def slot(pos: int) -> int:
        return (1 + pos // page_size) * page_size + pos % page_size

    t = len(PROMPT)
    tokens = jnp.asarray([PROMPT], jnp.int32)
    positions = jnp.arange(t, dtype=jnp.int32)[None, :]
    slots = jnp.asarray([[slot(p) for p in range(t)]], jnp.int32)
    logits, k_cache, v_cache = llama.forward(
        params, cfg, tokens, positions, k_cache, v_cache, tables, slots,
        jnp.asarray([t - 1], jnp.int32),
    )
    outs = [np.asarray(logits)[0]]
    for i, tok in enumerate(extra or []):
        pos = t + i
        logits, k_cache, v_cache = llama.forward(
            params, cfg,
            jnp.asarray([[tok]], jnp.int32),
            jnp.asarray([[pos]], jnp.int32),
            k_cache, v_cache, tables,
            jnp.asarray([[slot(pos)]], jnp.int32),
            jnp.asarray([0], jnp.int32),
        )
        outs.append(np.asarray(logits)[0])
    return outs


def _assert_family_matches(model, tmp_path, atol=2e-3):
    _save(model, tmp_path)
    hf = _hf_logits(model, extra=[11, 29])
    ours = _our_forward(tmp_path, extra=[11, 29])
    t = len(PROMPT)
    # Prefill: logits at the prompt's last position; then two decode steps.
    for step, pos in enumerate([t - 1, t, t + 1]):
        np.testing.assert_allclose(
            ours[step], hf[pos], atol=atol, rtol=1e-3,
            err_msg=f"step {step} (hf position {pos})",
        )


def test_golden_llama_gqa(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, tie_word_embeddings=False, rope_theta=10000.0,
    ))
    _assert_family_matches(m, tmp_path)


def test_golden_llama3_rope_scaling(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(1)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, tie_word_embeddings=True, rope_theta=500000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
                      "high_freq_factor": 4.0, "original_max_position_embeddings": 64},
    ))
    _assert_family_matches(m, tmp_path)


def test_golden_qwen2_bias(tmp_path):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(2)
    m = Qwen2ForCausalLM(Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False, rope_theta=1000000.0,
    ))
    _assert_family_matches(m, tmp_path)


def test_golden_mixtral_moe(tmp_path):
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(3)
    m = MixtralForCausalLM(MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2, tie_word_embeddings=False,
    ))
    _assert_family_matches(m, tmp_path)


def test_golden_qwen2_moe_shared_expert(tmp_path):
    """Qwen2-MoE: softmax routing WITHOUT top-k renormalization
    (norm_topk_prob=False) plus the sigmoid-gated always-on shared expert."""
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

    torch.manual_seed(6)
    m = Qwen2MoeForCausalLM(Qwen2MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        shared_expert_intermediate_size=48, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[], tie_word_embeddings=False,
    ))
    _assert_family_matches(m, tmp_path)


def test_golden_deepseek_v3_true_shape(tmp_path):
    """DeepSeek-V3's ACTUAL architecture in one model: MLA attention
    (interleaved rope), sigmoid routing with the aux-free correction bias
    (noaux_tc), group-limited top-k, routed scaling, a shared expert, and a
    leading dense layer (first_k_dense_replace=1) — BASELINE tracked config
    #4's semantics at test scale."""
    from transformers.models.deepseek_v3 import DeepseekV3Config, DeepseekV3ForCausalLM

    torch.manual_seed(5)
    m = DeepseekV3ForCausalLM(DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=4,
        q_lora_rank=32, kv_lora_rank=24, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, first_k_dense_replace=1,
        n_routed_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        n_shared_experts=1, n_group=2, topk_group=1, topk_method="noaux_tc",
        routed_scaling_factor=2.5, norm_topk_prob=True,
        rope_interleave=True, tie_word_embeddings=False, rope_scaling=None,
        attention_bias=False,
    ))
    # Deliberately NO scoring_func kwarg: native DeepseekV3Config does not
    # serialize it (its modeling hardcodes sigmoid), so this golden pins the
    # from_hf model_type→sigmoid fallback rather than an explicit key.
    # Random correction bias so the noaux_tc path is load-bearing.
    with torch.no_grad():
        for layer in m.model.layers[1:]:
            layer.mlp.gate.e_score_correction_bias.uniform_(-0.2, 0.2)
    _assert_family_matches(m, tmp_path)


def test_golden_deepseek_v2_group_limited_greedy(tmp_path):
    """DeepSeek-V2 routing semantics: softmax scoring, group_limited_greedy
    (groups ranked by per-group MAX, not V3's top-2 sum), no correction
    bias, unnormalized weights with routed scaling."""
    from transformers.models.deepseek_v2 import DeepseekV2Config, DeepseekV2ForCausalLM

    torch.manual_seed(7)
    m = DeepseekV2ForCausalLM(DeepseekV2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=4,
        q_lora_rank=32, kv_lora_rank=24, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, first_k_dense_replace=1,
        n_routed_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        n_shared_experts=1, n_group=2, topk_group=1,
        topk_method="group_limited_greedy", routed_scaling_factor=1.0,
        norm_topk_prob=False, tie_word_embeddings=False, rope_scaling=None,
        attention_bias=False,
    ))
    _assert_family_matches(m, tmp_path)


def test_golden_deepseek_mla_dense(tmp_path):
    """MLA attention (q/kv low-rank, rope_interleave=True checkpoint layout)
    with dense MLPs (first_k_dense_replace covers every layer) — isolates
    the MLA + interleave-permutation path against HF's modeling."""
    from transformers.models.deepseek_v3 import DeepseekV3Config, DeepseekV3ForCausalLM

    torch.manual_seed(4)
    m = DeepseekV3ForCausalLM(DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        q_lora_rank=32, kv_lora_rank=24, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, first_k_dense_replace=2,
        n_routed_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        n_shared_experts=1, rope_interleave=True, tie_word_embeddings=False,
        rope_scaling=None, attention_bias=False,
    ))
    _assert_family_matches(m, tmp_path)


def test_golden_qwen3_qk_norm(tmp_path):
    """Qwen3: per-head Q/K RMS norm before rope (head_dim-wide weights)."""
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(8)
    m = Qwen3ForCausalLM(Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, tie_word_embeddings=False, rope_theta=1000000.0,
    ))
    # Random norm weights so the qk-norm path is load-bearing.
    with torch.no_grad():
        for layer in m.model.layers:
            layer.self_attn.q_norm.weight.uniform_(0.5, 1.5)
            layer.self_attn.k_norm.weight.uniform_(0.5, 1.5)
    _assert_family_matches(m, tmp_path)


def test_golden_olmoe_flat_qk_norm(tmp_path):
    """OLMoE: flat Q/K RMS norm over the full projection width, plus its
    64-expert top-8 softmax routing (norm_topk_prob=False) — the family the
    on-chip MoE bench models."""
    from transformers import OlmoeConfig, OlmoeForCausalLM

    torch.manual_seed(9)
    m = OlmoeForCausalLM(OlmoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=8, num_experts_per_tok=2, norm_topk_prob=False,
        tie_word_embeddings=False,
    ))
    with torch.no_grad():
        for layer in m.model.layers:
            layer.self_attn.q_norm.weight.uniform_(0.5, 1.5)
            layer.self_attn.k_norm.weight.uniform_(0.5, 1.5)
    _assert_family_matches(m, tmp_path)


def test_golden_mistral_sliding_window(tmp_path):
    """Mistral: sliding-window attention with a window SHORTER than the
    prompt (w=4 < 8 tokens), so the windowed mask is load-bearing — full
    causal attention would produce different logits."""
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(10)
    m = MistralForCausalLM(MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, sliding_window=4, tie_word_embeddings=False,
        rope_theta=10000.0,
    ))
    _assert_family_matches(m, tmp_path)
    from dynamo_tpu.models.config import ModelConfig

    assert ModelConfig.from_hf(tmp_path / "config.json").sliding_window == 4


def test_golden_gemma(tmp_path):
    """Gemma family: GeGLU (gelu_pytorch_tanh) MLP, zero-centered (1+w)
    norm weights, sqrt(hidden) embedding scaling, tied head."""
    from transformers import GemmaConfig, GemmaForCausalLM

    torch.manual_seed(0)
    m = GemmaForCausalLM(GemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=10000.0, tie_word_embeddings=True,
        hidden_activation="gelu_pytorch_tanh",
    ))
    _save(m, tmp_path)
    cfg = ModelConfig.from_hf(tmp_path / "config.json")
    assert cfg.mlp_act == "gelu_tanh" and cfg.norm_plus_one and cfg.embed_scale
    _assert_family_matches(m, tmp_path)


def test_gemma_save_load_round_trip(tmp_path):
    """save_params pins model_type 'gemma' so the family math survives a
    save->load cycle; gemma2/3 configs are rejected loudly (softcapping +
    alternating windows are not Gemma-1 math)."""
    from transformers import GemmaConfig, GemmaForCausalLM

    from dynamo_tpu.models.loader import save_params

    torch.manual_seed(1)
    m = GemmaForCausalLM(GemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=10000.0, tie_word_embeddings=True,
        hidden_activation="gelu_pytorch_tanh",
    ))
    _save(m, tmp_path)
    cfg = ModelConfig.from_hf(tmp_path / "config.json")
    params = load_params(tmp_path, cfg, dtype="float32")
    out = tmp_path / "resaved"
    save_params(out, cfg, params)
    cfg2 = ModelConfig.from_hf(out / "config.json")
    assert cfg2.mlp_act == "gelu_tanh" and cfg2.norm_plus_one and cfg2.embed_scale
    params2 = load_params(out, cfg2, dtype="float32")
    a, b = __import__("jax").tree.leaves(params), __import__("jax").tree.leaves(params2)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)

    with pytest.raises(ValueError, match="gemma2"):
        ModelConfig.from_hf({"model_type": "gemma2", "hidden_size": 64,
                             "num_attention_heads": 4, "num_hidden_layers": 2,
                             "vocab_size": 8, "intermediate_size": 8,
                             "num_key_value_heads": 2})
