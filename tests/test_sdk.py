"""SDK service-graph DSL: decorators, topology, in-process serving, config
cascade, the packaged LLM graph, and the multi-process fleet path.

Parity model: reference SDK unit tests cover decorator metadata and config
cascade; here the serving path is additionally driven end-to-end on the
in-memory runtime and as real subprocesses over the TCP store/transport.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from dynamo_tpu.sdk import ServiceClient, api, depends, endpoint, service, spec_of
from dynamo_tpu.sdk.graph import build_graph, load_graph
from dynamo_tpu.sdk.serving import load_service_config, serve_graph


@service(namespace="t", resources={"tpu": 2}, replicas=3)
class Echo:
    @endpoint()
    async def generate(self, request, context):
        for ch in str(request.get("text", "")):
            yield {"ch": ch}

    @endpoint(name="ping")
    async def do_ping(self, request):
        return {"pong": True}


@service(namespace="t")
class Gateway:
    echo = depends(Echo)

    @api(path="/echo")
    async def echo_api(self, body):
        out = ""
        async for item in self.echo.generate(body):
            out += item["ch"]
        return {"text": out}


def test_decorator_metadata():
    spec = spec_of(Echo)
    assert spec.name == "Echo" and spec.namespace == "t"
    assert spec.resources == {"tpu": 2} and spec.replicas == 3
    assert [e.name for e in spec.endpoints] == ["generate", "ping"]
    spec_g = spec_of(Gateway)
    assert list(spec_g.dependencies) == ["echo"]
    assert [(a.http_method, a.path) for a in spec_g.apis] == [("POST", "/echo")]


def test_graph_topology_leaves_first():
    g = build_graph(Gateway)
    assert [s.name for s in g.services] == ["Echo", "Gateway"]
    assert g.edges() == [("Gateway", "Echo")]
    assert "Gateway" in g.describe()


def test_graph_cycle_detected():
    @service
    class A:
        pass

    @service
    class B:
        a = depends(A)

    # create a cycle after definition
    spec_of(A).dependencies["b"] = depends(B)
    with pytest.raises(ValueError, match="cycle"):
        build_graph(B)


def test_load_graph_ref():
    g = load_graph("dynamo_tpu.sdk.graphs:Frontend")
    assert [s.name for s in g.services] == ["Worker", "Processor", "Frontend"]


def test_unbound_dependency_raises():
    gw = Gateway()
    with pytest.raises(RuntimeError, match="not bound"):
        gw.echo  # noqa: B018


def test_config_cascade(tmp_path):
    cfg = tmp_path / "svc.yaml"
    cfg.write_text(
        textwrap.dedent(
            """
            Worker:
              model: test-tiny
              replicas: 2
            Frontend:
              http_port: 8123
            """
        )
    )
    merged = load_service_config(cfg, env={"DYN_SVC_WORKER_MODEL": '"llama-3.2-1b"', "DYN_SVC_WORKER_NUM_PAGES": "64"})
    assert merged["Worker"]["model"] == "llama-3.2-1b"  # env beats file
    assert merged["Worker"]["num_pages"] == 64
    assert merged["Worker"]["replicas"] == 2
    assert merged["Frontend"]["http_port"] == 8123
    # CamelCase / underscored service names still match at underscore splits
    merged2 = load_service_config(None, env={"DYN_SVC_KV_ROUTER_REPLICAS": "3"})
    assert merged2["KV"]["router_replicas"] == 3  # no section: first-token bucket
    cfg2 = tmp_path / "svc2.yaml"
    cfg2.write_text("KvRouter: {}\n")
    merged3 = load_service_config(cfg2, env={"DYN_SVC_KV_ROUTER_REPLICAS": "3"})
    assert merged3["KvRouter"]["replicas"] == 3


async def test_serve_graph_in_process():
    handles = await serve_graph(build_graph(Gateway))
    try:
        gw = handles.get("Gateway").obj
        assert isinstance(gw.echo, ServiceClient)
        out = ""
        async for item in gw.echo.generate({"text": "hi!"}):
            out += item["ch"]
        assert out == "hi!"
        # single-response endpoint becomes a one-item stream
        items = [i async for i in gw.echo.ping({})]
        assert items == [{"pong": True}]
        # the @api surface is live over real HTTP
        port = handles.get("Gateway").http_port
        assert port
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.post(f"http://127.0.0.1:{port}/echo", json={"text": "abc"}) as resp:
                assert resp.status == 200
                assert await resp.json() == {"text": "abc"}
    finally:
        await handles.close()


async def test_llm_graph_end_to_end_mock():
    g = load_graph("dynamo_tpu.sdk.graphs:Frontend")
    config = {"Worker": {"mock": True, "model": "test-tiny"}}
    handles = await serve_graph(g, config=config)
    try:
        port = handles.get("Frontend").http_port
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.post(
                f"http://127.0.0.1:{port}/generate",
                json={"prompt": "hello", "max_tokens": 4},
            ) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/event-stream")
                body = await resp.text()
        events = [json.loads(line[6:]) for line in body.splitlines() if line.startswith("data: ") and line != "data: [DONE]"]
        assert events, body
        assert events[-1].get("finish_reason")
    finally:
        await handles.close()


def test_build_archive_roundtrip(tmp_path, monkeypatch):
    """`dynamo build` packages user graph modules + manifest; the extracted
    src/ tree is genuinely importable on a deploy host (framework installed,
    archive sources on sys.path)."""
    import subprocess
    import sys

    from dynamo_tpu.sdk.build import build_archive, load_archive

    # a user graph package, outside dynamo_tpu
    pkg = tmp_path / "proj" / "mygraphs"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "agg.py").write_text(
        "from dynamo_tpu.sdk import api, depends, endpoint, service\n\n"
        "@service(namespace='u')\n"
        "class Worker:\n"
        "    @endpoint()\n"
        "    async def generate(self, request, context):\n"
        "        yield {'ok': True}\n\n"
        "@service(namespace='u')\n"
        "class Frontend:\n"
        "    worker = depends(Worker)\n"
    )
    monkeypatch.syspath_prepend(str(tmp_path / "proj"))
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("Worker:\n  model: test-tiny\n")
    out = build_archive(
        "mygraphs.agg:Frontend", config_path=str(cfg), output=str(tmp_path / "agg.tar.gz"),
    )
    assert out.exists()
    manifest = load_archive(out, tmp_path / "x")
    assert manifest["graph"] == "mygraphs.agg:Frontend"
    assert [s["name"] for s in manifest["services"]] == ["Worker", "Frontend"]
    assert manifest["config"]["Worker"]["model"] == "test-tiny"
    src_root = tmp_path / "x" / "src"
    assert (src_root / "mygraphs" / "agg.py").exists()
    assert (src_root / "mygraphs" / "__init__.py").exists()
    # deploy-host import: installed framework + ONLY the extracted sources
    import pathlib

    repo_root = pathlib.Path(__file__).resolve().parents[1]
    env = {"PYTHONPATH": f"{src_root}:{repo_root}", "JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin"}
    check = subprocess.run(
        [sys.executable, "-c",
         "from dynamo_tpu.sdk.graph import load_graph; "
         "g = load_graph('mygraphs.agg:Frontend'); "
         "print([s.name for s in g.services])"],
        capture_output=True, text=True, env=env,
    )
    assert "['Worker', 'Frontend']" in check.stdout, check.stderr


async def test_serve_fleet_subprocesses(tmp_path):
    """serve_entry subprocess + store server + TCP transport, called from a
    separate client process-side runtime."""
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.store_server import StoreClient, StoreServer
    from dynamo_tpu.runtime.tcp import TcpTransport

    server = await StoreServer(host="127.0.0.1", port=0).start()
    store_port = server.port
    cfg = tmp_path / "svc.yaml"
    cfg.write_text("Worker:\n  mock: true\n  model: test-tiny\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(filter(None, [env.get("PYTHONPATH"), os.getcwd()]))
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dynamo_tpu.sdk.serve_entry",
            "dynamo_tpu.sdk.graphs:Frontend", "--service", "Worker",
            "--store", f"tcp://127.0.0.1:{store_port}", "-f", str(cfg),
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        runtime = DistributedRuntime(
            StoreClient.from_url(f"tcp://127.0.0.1:{store_port}"), TcpTransport(host="127.0.0.1")
        )
        client = await (
            runtime.namespace("inference").component("worker").endpoint("generate").client().start()
        )
        # wait for the instance record to land
        for _ in range(100):
            if client.instance_ids():
                break
            await asyncio.sleep(0.2)
            assert proc.poll() is None, proc.stdout.read()
        assert client.instance_ids()
        req = {
            "token_ids": [1, 2, 3],
            "sampling_options": {},
            "stop_conditions": {"max_tokens": 3},
        }
        outs = [o async for o in client.generate(req)]
        assert outs and any(o.get("token_ids") for o in outs)
        await client.close()
        await runtime.close()
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        await server.close()
