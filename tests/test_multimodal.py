"""Multimodal tests: vision tower, embedding injection, encode->prefill e2e.

Parity: reference `examples/multimodal/` (encode worker -> embeddings ->
prefill handoff), rebuilt first-party (SURVEY.md §2 row 51).
"""

import numpy as np
import pytest

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions

CFG = PRESETS["test-tiny-vl"]
IMG = CFG.image_token_id


def _run(core, token_ids, mm_inputs=None, max_tokens=6):
    req = PreprocessedRequest(
        token_ids=token_ids,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        mm_inputs=mm_inputs,
    )
    seq = core.add_request(req)
    while not seq.is_finished:
        core.step()
    return seq


def _mm_payload(embeds: np.ndarray) -> dict:
    import base64

    return {
        "embeds_b64": base64.b64encode(np.ascontiguousarray(embeds, np.float32).tobytes()).decode(),
        "shape": list(embeds.shape),
        "dtype": "float32",
    }


def _core(params, **kw):
    runner = ModelRunner(CFG, params, num_pages=64, page_size=4, max_batch_size=4)
    return EngineCore(runner, EngineConfig(num_pages=64, page_size=4, max_batch_size=4,
                                           enable_prefix_caching=False, **kw))


def test_injection_equals_token_embedding():
    """Placeholders fed the embedding rows of token 7 must generate exactly
    what the prompt with literal token 7s generates (the substitution is the
    whole mechanism; greedy decode makes it observable token-exactly)."""
    params = llama.init_params(CFG, 0)
    embed_row_7 = np.asarray(params["embed"][7], np.float32)

    prompt_img = [5, 6, IMG, IMG, 9, 10, 11, 12]
    prompt_tok = [5, 6, 7, 7, 9, 10, 11, 12]
    mm = np.stack([embed_row_7, embed_row_7])  # one row per placeholder

    seq_a = _run(_core(params), prompt_img, mm_inputs=_mm_payload(mm))
    seq_b = _run(_core(params), prompt_tok)
    assert seq_a.finish_reason is not None and seq_a.finish_reason.value == "length"
    assert seq_a.tokens[len(prompt_img):] == seq_b.tokens[len(prompt_tok):]


def test_injection_embeddings_matter():
    """Different image embeddings -> different greedy output."""
    rng = np.random.default_rng(3)
    params = llama.init_params(CFG, 0)
    prompt = [5, 6, IMG, IMG, 9, 10, 11, 12]
    mm = rng.standard_normal((2, CFG.hidden_size)).astype(np.float32)
    mm2 = rng.standard_normal((2, CFG.hidden_size)).astype(np.float32) * 3
    a = _run(_core(params), prompt, mm_inputs=_mm_payload(mm))
    b = _run(_core(params), prompt, mm_inputs=_mm_payload(mm2))
    assert a.tokens[len(prompt):] != b.tokens[len(prompt):]


def test_forward_offset_resumed_chunk_equals_whole():
    """The mm slot offset: prefilling the tail of a prompt whose earlier
    chunk (with 2 placeholders) is already cached must inject rows 2,3 —
    logits must equal the single-pass whole-prompt prefill."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    params = llama.init_params(CFG, 0)
    prompt = np.array([5, 6, IMG, IMG, 9, 10, 11, 12, 20, 21, 22, 23, 24, IMG, IMG, 25], np.int32)
    mm = jnp.asarray(rng.standard_normal((1, 4, CFG.hidden_size)).astype(np.float32))
    ps, pages = 4, [1, 2, 3, 4]
    tables = np.asarray([pages], np.int32)

    def run(tokens, positions, k, v, offset, counts):
        slots = np.asarray([[pages[p // ps] * ps + p % ps for p in positions[0]]], np.int32)
        return llama.forward(
            params, CFG, jnp.asarray(tokens), jnp.asarray(positions), k, v,
            jnp.asarray(tables), jnp.asarray(slots),
            jnp.asarray([tokens.shape[1] - 1], np.int32),
            mm_embeds=mm, mm_slot_offset=jnp.asarray([offset], np.int32),
            mm_counts=jnp.asarray([counts], np.int32),
        )

    k0, v0 = llama.init_kv_cache(CFG, 8, ps)
    logits_whole, _, _ = run(prompt[None, :], np.arange(16, dtype=np.int32)[None, :], k0, v0, 0, 4)

    k1, v1 = llama.init_kv_cache(CFG, 8, ps)
    _, k1, v1 = run(prompt[None, :8], np.arange(8, dtype=np.int32)[None, :], k1, v1, 0, 4)
    # Resume at position 8 with 2 placeholders already cached: offset=2.
    logits_tail, _, _ = run(prompt[None, 8:], np.arange(8, 16, dtype=np.int32)[None, :], k1, v1, 2, 4)
    np.testing.assert_allclose(np.asarray(logits_tail), np.asarray(logits_whole), rtol=2e-4, atol=2e-4)


def test_text_row_with_placeholder_id_unaffected_by_mm_batchmate():
    """A text prompt that *contains* the placeholder id, prefilled in the
    same batch as a real multimodal request, must keep its normal token
    embeddings (no zero-row substitution leaking across batch rows)."""
    params = llama.init_params(CFG, 0)
    text_prompt = [5, IMG, 6, 7]  # pre-tokenized prompt using the raw id

    solo = _run(_core(params), text_prompt)

    core = _core(params)
    mm = np.random.default_rng(1).standard_normal((2, CFG.hidden_size)).astype(np.float32)
    req_mm = PreprocessedRequest(
        token_ids=[8, IMG, IMG, 9],
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=6, ignore_eos=True),
        mm_inputs=_mm_payload(mm),
    )
    req_text = PreprocessedRequest(
        token_ids=list(text_prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=6, ignore_eos=True),
    )
    seq_mm = core.add_request(req_mm)
    seq_text = core.add_request(req_text)
    while not (seq_mm.is_finished and seq_text.is_finished):
        core.step()
    assert seq_text.tokens[len(text_prompt):] == solo.tokens[len(text_prompt):]


def test_mm_overlap_chained_decode_bit_identical():
    """Multimodal rows ride the chained pipeline: overlap on/off must be
    token-identical with the pipeline actually engaged. mm_embeds only feed
    prefill chunks; chained decode of an mm row is plain decode, so there is
    no 'mm' barrier reason anymore — assert it stayed dead."""
    params = llama.init_params(CFG, 0)
    mm = np.random.default_rng(7).standard_normal((2, CFG.hidden_size)).astype(np.float32)

    def run(overlap):
        core = _core(params, overlap=overlap, chunk_prefill_tokens=4, max_seq_len=64)
        seqs = [
            core.add_request(PreprocessedRequest(
                token_ids=[5, 6, IMG, IMG, 9, 10, 11, 12],
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=10, ignore_eos=True),
                mm_inputs=_mm_payload(mm),
            )),
            core.add_request(PreprocessedRequest(
                token_ids=[3, 4, 5, 6],
                sampling=SamplingOptions(temperature=0.8, seed=11),
                stop=StopConditions(max_tokens=8, ignore_eos=True),
            )),
        ]
        while core.has_work:
            core.step()
        return [s.tokens for s in seqs], core

    base, _ = run(False)
    over, core = run(True)
    assert over == base
    assert core.overlap_step_counts["overlapped"] > 0
    assert "mm" not in core.overlap_barrier_counts
    assert core.allocator.stats().active_pages == 0


def test_mrope_overlap_chained_decode_bit_identical():
    """M-RoPE chained decode: the 3-axis positions of a chained token are
    derived in-graph (pos + per-row mrope delta on all three axes), so an
    image request on an M-RoPE model must decode through the overlapped
    pipeline bit-identically to the sync loop."""
    import dataclasses

    cfg = dataclasses.replace(
        PRESETS["test-tiny"], mrope_section=(2, 3, 3), image_token_id=250,
    )
    params = llama.init_params(cfg, 3)
    runner = ModelRunner(cfg, params, num_pages=64, page_size=4, max_batch_size=4)
    mm = np.random.default_rng(9).standard_normal((4, cfg.hidden_size)).astype(np.float32)
    payload = {**_mm_payload(mm), "grids": [[1, 4, 4]]}  # 4 merged placeholders

    def run(overlap):
        core = EngineCore(runner, EngineConfig(
            num_pages=64, page_size=4, max_batch_size=4, max_seq_len=64,
            chunk_prefill_tokens=4, enable_prefix_caching=False, overlap=overlap,
        ))
        seqs = [
            core.add_request(PreprocessedRequest(
                token_ids=[5, 6, 250, 250, 250, 250, 9, 10],
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=10, ignore_eos=True),
                mm_inputs=payload,
            )),
            core.add_request(PreprocessedRequest(
                token_ids=[3, 4, 5, 6, 7, 8],
                sampling=SamplingOptions(temperature=0.7, seed=13, logprobs=2),
                stop=StopConditions(max_tokens=8, ignore_eos=True),
            )),
        ]
        lps = {s.seq_id: [] for s in seqs}
        while core.has_work:
            for seq, out in core.step():
                if out.logprobs:
                    lps[seq.seq_id].extend(out.logprobs)
        return [(s.tokens, lps[s.seq_id]) for s in seqs], core

    base, _ = run(False)
    over, core = run(True)
    assert over == base
    assert core.overlap_step_counts["overlapped"] > 0
    assert core.allocator.stats().active_pages == 0


def test_malformed_mm_inputs_fail_only_that_request():
    params = llama.init_params(CFG, 0)
    core = _core(params)
    bad = _run(core, [5, IMG, 6], mm_inputs={"embeds_b64": "AA=="}, max_tokens=2)  # no shape
    assert bad.finish_reason is not None and bad.finish_reason.value == "error"
    good = _run(core, [5, 6, 7, 8], max_tokens=2)  # engine still serves
    assert good.finish_reason is not None and good.finish_reason.value == "length"


def test_router_salt_fold_matches_engine():
    """The KV router must look up multimodal requests with the same folded
    salt the engine publishes, or image-affine routing never matches."""
    from dynamo_tpu.tokens import DEFAULT_SALT, compute_block_hashes, mm_salt_fold

    mm = np.ones((2, CFG.hidden_size), np.float32)
    payload = _mm_payload(mm)
    fold = mm_salt_fold(payload)
    assert fold != 0
    assert mm_salt_fold(None) == 0 and mm_salt_fold({}) == 0
    toks = [5, IMG, IMG, 6, 7, 8, 9, 10]
    engine_side = compute_block_hashes(toks, 4, salt=DEFAULT_SALT ^ fold)
    router_side = compute_block_hashes(toks, 4, salt=DEFAULT_SALT ^ mm_salt_fold(payload))
    assert engine_side == router_side
    assert engine_side != compute_block_hashes(toks, 4, salt=DEFAULT_SALT)


def test_mismatched_placeholder_count_rejected():
    params = llama.init_params(CFG, 0)
    core = _core(params)
    mm = np.zeros((3, CFG.hidden_size), np.float32)  # 3 rows, 2 placeholders
    seq = _run(core, [5, IMG, IMG, 9], mm_inputs=_mm_payload(mm), max_tokens=2)
    assert seq.finish_reason is not None and seq.finish_reason.value == "error"


def test_vision_tower_shapes_and_determinism():
    import jax.numpy as jnp

    from dynamo_tpu.models.vision import TEST_TINY_VISION, encode_image, init_vision_params

    vp = init_vision_params(TEST_TINY_VISION, 0)
    pixels = np.random.default_rng(0).uniform(-1, 1, (2, 32, 32, 3)).astype(np.float32)
    out = encode_image(vp, TEST_TINY_VISION, jnp.asarray(pixels))
    assert out.shape == (2, TEST_TINY_VISION.num_patches, TEST_TINY_VISION.out_dim)
    out2 = encode_image(vp, TEST_TINY_VISION, jnp.asarray(pixels))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # Different images -> different embeddings.
    assert not np.allclose(np.asarray(out)[0], np.asarray(out)[1])


def test_image_preprocess_and_data_url():
    import base64
    import io

    from PIL import Image

    from dynamo_tpu.models.vision import TEST_TINY_VISION, decode_data_url, preprocess_image

    img = Image.new("RGB", (64, 48), (255, 0, 0))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    url = "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()
    arr = preprocess_image(decode_data_url(url), TEST_TINY_VISION)
    assert arr.shape == (32, 32, 3)
    assert arr.max() <= 1.0 and arr.min() >= -1.0
    assert arr[0, 0, 0] > 0.9  # red channel saturated

    with pytest.raises(ValueError):
        decode_data_url("https://example.com/cat.png")


async def test_multimodal_chat_e2e():
    """Full loop over HTTP: chat with a data-URL image -> encode worker ->
    embeddings -> placeholder-spliced prompt -> injected prefill -> tokens.
    Different images must produce different outputs (the pixels matter)."""
    import base64
    import io

    import aiohttp
    from PIL import Image

    from dynamo_tpu.launch import run_local

    def data_url(color):
        img = Image.new("RGB", (32, 32), color)
        buf = io.BytesIO()
        img.save(buf, format="PNG")
        return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()

    handles = await run_local("test-tiny-vl", port=0, num_pages=128, max_batch_size=4)
    base = f"http://127.0.0.1:{handles['port']}"
    try:
        async def ask(color):
            body = {
                "model": "test-tiny-vl",
                "messages": [{"role": "user", "content": [
                    {"type": "text", "text": "what is this? "},
                    {"type": "image_url", "image_url": {"url": data_url(color)}},
                ]}],
                "max_tokens": 6, "temperature": 0,
            }
            async with aiohttp.ClientSession() as s:
                async with s.post(base + "/v1/chat/completions", json=body) as r:
                    assert r.status == 200, await r.text()
                    out = await r.json()
            return out

        red = await ask((255, 0, 0))
        red2 = await ask((255, 0, 0))
        blue = await ask((0, 0, 255))
        # Prompt accounting includes the image placeholder tokens.
        from dynamo_tpu.models.vision import TEST_TINY_VISION
        assert red["usage"]["prompt_tokens"] > TEST_TINY_VISION.num_patches
        assert red["choices"][0]["message"]["content"] == red2["choices"][0]["message"]["content"]
        assert red["choices"][0]["message"]["content"] != blue["choices"][0]["message"]["content"]

        # The encode worker actually served the images.
        from dynamo_tpu.encode import EncodeService
        enc = next(s for s in handles["services"] if isinstance(s, EncodeService))
        assert enc.images_encoded == 3
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
