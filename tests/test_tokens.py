"""Unit tests for token block hashing (dynamo_tpu.tokens).

Mirrors the reference test strategy for its tokens crate: chained hash
determinism, prefix stability, incremental-vs-batch equivalence.
"""

import numpy as np
import pytest

from dynamo_tpu.tokens import (
    DEFAULT_SALT,
    SaltedPrefix,
    TokenBlockSequence,
    compute_block_hashes,
    hash_token_block,
)


def test_hash_deterministic():
    h1 = hash_token_block([1, 2, 3, 4], None)
    h2 = hash_token_block([1, 2, 3, 4], None)
    assert h1 == h2
    assert isinstance(h1, int)
    assert 0 <= h1 < 2**64


def test_hash_depends_on_tokens_parent_salt():
    base = hash_token_block([1, 2, 3, 4], None)
    assert hash_token_block([1, 2, 3, 5], None) != base
    assert hash_token_block([1, 2, 3, 4], 7) != base
    assert hash_token_block([1, 2, 3, 4], None, salt=123) != base


def test_chained_hashes_prefix_property():
    """Shared prefixes produce identical leading block hashes; divergence changes the rest."""
    a = compute_block_hashes(list(range(64)), 16)
    b = compute_block_hashes(list(range(48)) + [999] * 16, 16)
    assert a[:3] == b[:3]
    assert a[3] != b[3]


def test_partial_block_excluded():
    assert compute_block_hashes(list(range(10)), 16) == []
    assert len(compute_block_hashes(list(range(16)), 16)) == 1
    assert len(compute_block_hashes(list(range(31)), 16)) == 1
    assert len(compute_block_hashes(list(range(32)), 16)) == 2


def test_numpy_and_list_inputs_agree():
    toks = list(range(32))
    assert compute_block_hashes(toks, 16) == compute_block_hashes(np.array(toks, dtype=np.int32), 16)
    assert compute_block_hashes(toks, 16) == compute_block_hashes(np.array(toks, dtype=np.int64), 16)


def test_incremental_sequence_matches_batch():
    toks = list(np.random.default_rng(0).integers(0, 32000, size=100))
    seq = TokenBlockSequence(block_size=16)
    committed = []
    for t in toks:
        blk = seq.append(t)
        if blk is not None:
            committed.append(blk.block_hash)
    assert committed == compute_block_hashes(toks, 16)
    assert len(seq) == 100
    assert len(seq.partial_tokens) == 100 % 16
    np.testing.assert_array_equal(seq.tokens, np.asarray(toks, dtype=np.int32))


def test_sequence_extend_and_positions():
    seq = TokenBlockSequence(list(range(40)), block_size=16)
    assert [b.position for b in seq.blocks] == [0, 1]
    assert seq.blocks[0].parent_hash is None
    assert seq.blocks[1].parent_hash == seq.blocks[0].block_hash


def test_sequence_truncate():
    toks = list(range(100))
    seq = TokenBlockSequence(toks, block_size=16)
    seq.truncate(40)
    assert len(seq) == 40
    assert seq.block_hashes == compute_block_hashes(toks[:40], 16)
    with pytest.raises(ValueError):
        seq.truncate(41)


def test_block_size_validation():
    with pytest.raises(ValueError):
        compute_block_hashes([1, 2], 0)
    with pytest.raises(ValueError):
        TokenBlockSequence(block_size=-1)


def test_salted_prefix_model_separation():
    s1 = SaltedPrefix("meta-llama/Llama-3.2-1B").salt
    s2 = SaltedPrefix("Qwen/Qwen2-7B").salt
    assert s1 != s2
    assert SaltedPrefix("meta-llama/Llama-3.2-1B").salt == s1
    h1 = compute_block_hashes(list(range(16)), 16, salt=s1)
    h2 = compute_block_hashes(list(range(16)), 16, salt=s2)
    assert h1 != h2
    assert SaltedPrefix("x", base_salt=DEFAULT_SALT).salt != DEFAULT_SALT
