"""Config cascade, JSONL logging, and llmctl tests.

Parity: reference figment config (`config.rs:26-143`), tracing init with
env toggles (`logging.rs`, `config.rs:163-176`), and the llmctl CLI.
"""

import io
import json
import logging

from dynamo_tpu.config import RuntimeSettings, WorkerSettings, load_runtime_settings, load_worker_settings


def test_config_defaults():
    s = load_runtime_settings(env={})
    assert s == RuntimeSettings()


def test_config_toml_layer(tmp_path):
    f = tmp_path / "dyn.toml"
    f.write_text("""
[runtime]
http_port = 9191
log_jsonl = true

[worker]
model = "llama-3-8b"
num_pages = 4096
""")
    r = load_runtime_settings(toml_path=f, env={})
    w = load_worker_settings(toml_path=f, env={})
    assert r.http_port == 9191 and r.log_jsonl is True
    assert w.model == "llama-3-8b" and w.num_pages == 4096
    assert w.max_batch_size == 64  # untouched default


def test_config_env_overrides_toml(tmp_path):
    f = tmp_path / "dyn.toml"
    f.write_text("[runtime]\nhttp_port = 9191\n")
    env = {"DYN_CONFIG": str(f), "DYN_RUNTIME_HTTP_PORT": "7777", "DYN_RUNTIME_LOG_JSONL": "1"}
    r = load_runtime_settings(env=env)  # file found via DYN_CONFIG
    assert r.http_port == 7777  # env wins over TOML
    assert r.log_jsonl is True  # bool coercion


def test_config_bad_env_value():
    import pytest

    with pytest.raises(ValueError, match="DYN_WORKER_NUM_PAGES"):
        load_worker_settings(env={"DYN_WORKER_NUM_PAGES": "not-a-number"})


def test_config_unknown_toml_key_warns(tmp_path, caplog):
    f = tmp_path / "dyn.toml"
    f.write_text("[worker]\nnot_a_field = 3\n")
    with caplog.at_level(logging.WARNING):
        w = load_worker_settings(toml_path=f, env={})
    assert w == WorkerSettings()
    assert any("unknown key" in r.message for r in caplog.records)


def test_jsonl_logging_format():
    from dynamo_tpu.runtime.logging import setup_logging

    buf = io.StringIO()
    handler = setup_logging(env={"DYN_LOGGING_JSONL": "1", "DYN_LOG_LEVEL": "DEBUG"}, stream=buf)
    try:
        log = logging.getLogger("dynamo_tpu.test.jsonl")
        log.info("hello %s", "world", extra={"request_id": "r-1", "worker": 7})
        log.debug("dbg")
        line1, line2 = buf.getvalue().strip().splitlines()
        d = json.loads(line1)
        assert d["message"] == "hello world"
        assert d["level"] == "INFO"
        assert d["target"] == "dynamo_tpu.test.jsonl"
        assert d["request_id"] == "r-1" and d["worker"] == 7
        assert d["time"].endswith("+00:00")  # UTC default
        assert json.loads(line2)["level"] == "DEBUG"
    finally:
        logging.getLogger().removeHandler(handler)


def test_text_logging_no_ansi_toggle():
    from dynamo_tpu.runtime.logging import setup_logging

    buf = io.StringIO()
    handler = setup_logging(env={"DYN_SDK_DISABLE_ANSI_LOGGING": "1"}, stream=buf)
    try:
        logging.getLogger("dynamo_tpu.test.txt").warning("plain")
        out = buf.getvalue()
        assert "plain" in out and "\x1b[" not in out
    finally:
        logging.getLogger().removeHandler(handler)


async def test_llmctl_add_list_remove(capsys):
    import argparse

    from dynamo_tpu.llmctl import _amain
    from dynamo_tpu.runtime.store_server import StoreServer

    server = await StoreServer(host="127.0.0.1", port=0).start()
    store_url = f"tcp://127.0.0.1:{server.port}"
    try:
        async def run(*argv):
            # The real CLI parser, driven in-loop (main() owns asyncio.run).
            ns = argparse.Namespace(store=store_url)
            cmd = argv[0]
            ns.cmd = cmd
            defaults = {
                "add": dict(tokenizer="byte", context_length=4096,
                            router_mode="round_robin", model_type="chat+completions"),
                "list": dict(json=False),
                "remove": {},
            }[cmd]
            for k, v in defaults.items():
                setattr(ns, k, v)
            it = iter(argv[1:])
            for flag in it:
                setattr(ns, flag.removeprefix("--").replace("-", "_"),
                        True if flag == "--json" else next(it))
            return await _amain(ns)

        assert await run("add", "--name", "ext-model", "--endpoint", "dynamo.backend.generate") == 0
        assert await run("list") == 0
        out = capsys.readouterr().out
        assert "ext-model" in out and "dynamo.backend.generate" in out

        assert await run("list", "--json") == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["ext-model"][0]["context_length"] == 4096

        assert await run("remove", "--name", "ext-model") == 0
        assert await run("list") == 0
        assert "(no models registered)" in capsys.readouterr().out
        assert await run("remove", "--name", "ext-model") == 1  # already gone
    finally:
        await server.close()


async def test_llmctl_deployment_commands(capsys):
    import argparse

    from dynamo_tpu.deploy.objects import GraphDeployment
    from dynamo_tpu.llmctl import _amain
    from dynamo_tpu.runtime.store_server import StoreClient, StoreServer

    server = await StoreServer(host="127.0.0.1", port=0).start()
    store_url = f"tcp://127.0.0.1:{server.port}"
    client = StoreClient.from_url(store_url)
    try:
        dep = GraphDeployment(name="agg", graph="graphs:Frontend")
        await client.put(dep.key, dep.to_bytes())

        async def run(dep_cmd, name=None, replicas=None, json_out=False):
            ns = argparse.Namespace(
                store=store_url, cmd="deployment", dep_cmd=dep_cmd,
                name=name, replicas=replicas, json=json_out,
            )
            return await _amain(ns)

        assert await run("list") == 0
        assert "agg" in capsys.readouterr().out
        assert await run("scale", name="agg", replicas="Worker=4") == 0
        capsys.readouterr()
        updated = GraphDeployment.from_bytes(await client.get(dep.key))
        assert updated.config["Worker"]["replicas"] == 4
        assert updated.generation == 2 and updated.phase == "pending"
        assert await run("delete", name="agg") == 0
        assert GraphDeployment.from_bytes(await client.get(dep.key)).phase == "deleting"
        assert await run("scale", name="agg", replicas="Worker=1") == 1  # deleting: refuse
        assert await run("scale", name="missing", replicas="W=1") == 1
    finally:
        close = getattr(client, "close", None)
        if close:
            await close()
        await server.close()


async def test_standalone_router_service():
    """The router-as-a-service answers schedule queries against a live
    worker fleet, preferring the worker whose cache holds the prefix."""
    from dynamo_tpu.launch import run_local
    from dynamo_tpu.router.service import serve_router
    from dynamo_tpu.runtime.engine import Context
    import aiohttp

    handles = await run_local("test-tiny", port=0, num_workers=2, mock=True,
                              num_pages=128, max_batch_size=8)
    try:
        router = await serve_router(handles["runtime"], block_size=16)
        # Warm one worker's cache through the normal serving path.
        base = f"http://127.0.0.1:{handles['port']}"
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "prompt": "z" * 48, "max_tokens": 2, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200

        from conftest import wait_for

        # Query via the served endpoint like an external gateway would,
        # with the same token ids the frontend sent (byte tokenizer).
        client = handles["runtime"].namespace("dynamo").component("router").endpoint("route").client()
        from dynamo_tpu.tokenizer import load_tokenizer

        prompt_ids = load_tokenizer("byte").encode("z" * 48, add_bos=True)

        assert await wait_for(lambda: router._push.router.indexer.num_blocks >= 2)
        async for resp in client.generate({"token_ids": prompt_ids}, Context()):
            break
        assert "worker_id" in resp, resp
        # The chosen worker is the one holding the cached prefix.
        assert resp["overlap_blocks"] >= 2, resp
        assert router.decisions == 1
        await router.close()
    finally:
        await handles["http"].stop()
        await handles["watcher"].close()
        for svc in handles["services"]:
            await svc.close()
        await handles["runtime"].close()
