"""GGUF support: binary round-trip, dequant correctness, config/tokenizer
extraction, params loading, and WorkerSpec resolution of a .gguf path.

The writer emits spec-conformant GGUF v3 (magic, typed metadata, reversed
ggml dims, aligned data section), so reading back through the parser proves
both directions against the format llama.cpp tools produce.
"""

import dataclasses
import struct

import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.models.gguf import (
    GGML_F16,
    GGML_Q4_0,
    GGML_Q8_0,
    GGUFReader,
    config_from_gguf,
    load_gguf_params,
    save_params_gguf,
    tokenizer_from_gguf,
    write_gguf,
)


def test_metadata_roundtrip(tmp_path):
    path = tmp_path / "m.gguf"
    md = {
        "general.architecture": "llama",
        "llama.block_count": 2,
        "llama.rope.freq_base": 10000.0,
        "flag": True,
        "tokenizer.ggml.tokens": ["a", "b", "c"],
        "tokenizer.ggml.scores": [0.0, -1.0, -2.0],
        "ids": [3, 1, 2],
    }
    write_gguf(path, md, {"t": np.arange(64, dtype=np.float32).reshape(8, 8)})
    r = GGUFReader(path)
    assert r.version == 3
    assert r.metadata["general.architecture"] == "llama"
    assert r.metadata["llama.block_count"] == 2
    assert r.metadata["flag"] is True
    assert r.metadata["tokenizer.ggml.tokens"] == ["a", "b", "c"]
    assert r.metadata["ids"] == [3, 1, 2]
    np.testing.assert_allclose(r.metadata["tokenizer.ggml.scores"], [0.0, -1.0, -2.0])
    r.close()


def test_tensor_dtypes_roundtrip(tmp_path):
    import ml_dtypes

    path = tmp_path / "t.gguf"
    rng = np.random.default_rng(0)
    f32 = rng.standard_normal((4, 32)).astype(np.float32)
    f16 = rng.standard_normal((64,)).astype(np.float16)
    bf16 = rng.standard_normal((2, 3, 32)).astype(ml_dtypes.bfloat16)
    write_gguf(path, {"general.architecture": "llama"}, {"f32": f32, "f16": f16, "bf16": bf16})
    r = GGUFReader(path)
    np.testing.assert_array_equal(r.read("f32"), f32)
    np.testing.assert_array_equal(r.read("f16"), f16)
    np.testing.assert_array_equal(np.asarray(r.read("bf16"), np.float32), np.asarray(bf16, np.float32))
    # shapes come back in numpy orientation despite reversed on-disk dims
    assert r.tensors["bf16"].shape == (2, 3, 32)
    r.close()


def test_q8_0_quant_roundtrip(tmp_path):
    path = tmp_path / "q.gguf"
    rng = np.random.default_rng(1)
    w = rng.standard_normal((16, 64)).astype(np.float32)
    write_gguf(path, {"general.architecture": "llama"}, {"w": w}, quant=GGML_Q8_0)
    r = GGUFReader(path)
    got = r.read("w")
    # int8 block quant: max error bounded by half a quant step per block
    err = np.abs(got - w)
    step = np.abs(w).reshape(-1, 32).max(axis=1) / 127.0
    assert (err.reshape(-1, 32) <= step[:, None] * 0.51 + 1e-6).all()
    r.close()


def test_q4_0_dequant_against_formula(tmp_path):
    # Hand-build one Q4_0 block: d=0.5, qs nibbles 0..15 twice
    d = np.float16(0.5)
    qs = bytes((i | (i << 4)) for i in range(16))  # low nibble i (elem i), high nibble i (elem i+16)
    raw = struct.pack("<e", d) + qs
    from dynamo_tpu.models.gguf import _dequant

    got = _dequant(raw, GGML_Q4_0, (32,))
    expect = np.concatenate([np.arange(16), np.arange(16)]).astype(np.float32)
    expect = (expect - 8.0) * 0.5
    np.testing.assert_allclose(got, expect)


def test_q4_0_writer_roundtrip(tmp_path):
    path = tmp_path / "q4.gguf"
    rng = np.random.default_rng(3)
    w = rng.standard_normal((8, 64)).astype(np.float32)
    write_gguf(path, {"general.architecture": "llama"}, {"w": w}, quant=GGML_Q4_0)
    r = GGUFReader(path)
    got = r.read("w")
    r.close()
    # 4-bit blocks: quants land within half a step except at the positive
    # extreme, where the asymmetric [-8, 7] range costs up to one full step
    step = np.abs(w).reshape(-1, 32).max(axis=1) / 8.0
    assert (np.abs(got - w).reshape(-1, 32) <= step[:, None] * 1.01 + 1e-6).all()


def test_alignment_key_not_duplicated(tmp_path):
    path = tmp_path / "al.gguf"
    v = np.arange(32, dtype=np.float32)
    write_gguf(path, {"general.architecture": "llama", "general.alignment": 64}, {"v": v})
    r = GGUFReader(path)
    assert r.metadata["general.alignment"] == 64
    np.testing.assert_array_equal(r.read("v"), v)  # data laid out at 64 too
    r.close()


def test_mixed_int_float_array_promotes(tmp_path):
    path = tmp_path / "mix.gguf"
    write_gguf(path, {"general.architecture": "llama", "scores": [0, -1.25, -2.5]}, {})
    r = GGUFReader(path)
    np.testing.assert_allclose(r.metadata["scores"], [0.0, -1.25, -2.5])
    r.close()


def test_rope_scaling_linear_and_yarn():
    from dynamo_tpu.ops.rope import rope_frequencies

    base = rope_frequencies(64, theta=10000.0)
    lin = rope_frequencies(64, theta=10000.0, scaling={"rope_type": "linear", "factor": 4.0})
    np.testing.assert_allclose(lin, base / 4.0, rtol=1e-6)
    yarn = rope_frequencies(
        64, theta=10000.0,
        scaling={"rope_type": "yarn", "factor": 4.0, "original_max_position_embeddings": 4096},
    )
    # high-frequency dims extrapolate (unchanged), low-frequency interpolate
    np.testing.assert_allclose(yarn[0], base[0], rtol=1e-6)
    np.testing.assert_allclose(yarn[-1], base[-1] / 4.0, rtol=1e-6)
    assert ((yarn <= base + 1e-9) & (yarn >= base / 4.0 - 1e-9)).all()
    with pytest.raises(ValueError, match="unsupported rope scaling"):
        rope_frequencies(64, scaling={"rope_type": "longrope", "factor": 2.0})

    from dynamo_tpu.ops.rope import rope_attention_factor

    assert rope_attention_factor(None) == 1.0
    assert rope_attention_factor({"rope_type": "llama3", "factor": 8.0}) == 1.0
    yf = rope_attention_factor({"rope_type": "yarn", "factor": 4.0})
    np.testing.assert_allclose(yf, 0.1 * np.log(4.0) + 1.0)
    assert rope_attention_factor({"rope_type": "yarn", "factor": 4.0, "attention_factor": 1.5}) == 1.5


def test_unblockable_quant_falls_back(tmp_path):
    path = tmp_path / "fb.gguf"
    v = np.arange(7, dtype=np.float32)  # 7 % 32 != 0 -> cannot block-quantize
    write_gguf(path, {"general.architecture": "llama"}, {"v": v}, quant=GGML_Q8_0)
    r = GGUFReader(path)
    assert r.tensors["v"].ggml_type == GGML_F16
    np.testing.assert_allclose(r.read("v"), v)
    r.close()


def _tok_metadata(kind="gpt2"):
    if kind == "gpt2":
        # Byte-level BPE over a tiny vocab: enough to encode "hello hello"
        vocab = ["h", "e", "l", "o", "Ġ", "he", "ll", "hell", "hello", "Ġhello"]
        merges = ["h e", "l l", "he ll", "hell o", "Ġ hello"]
        return {
            "tokenizer.ggml.model": "gpt2",
            "tokenizer.ggml.tokens": vocab,
            "tokenizer.ggml.merges": merges,
            "tokenizer.ggml.bos_token_id": 8,
            "tokenizer.ggml.eos_token_id": 8,
        }
    # unigram ("llama"-style) with metaspace pieces
    tokens = ["<unk>", "<s>", "</s>", "▁hello", "▁world", "▁", "h", "w", "o"]
    scores = [0.0, 0.0, 0.0, -1.0, -1.5, -2.0, -3.0, -3.0, -3.0]
    return {
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": tokens,
        "tokenizer.ggml.scores": scores,
        "tokenizer.ggml.unknown_token_id": 0,
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
    }


def test_embedded_bpe_tokenizer(tmp_path):
    path = tmp_path / "tok.gguf"
    write_gguf(path, {"general.architecture": "llama", **_tok_metadata("gpt2")}, {})
    r = GGUFReader(path)
    tok = tokenizer_from_gguf(r)
    ids = tok.encode("hello hello")
    assert tok.decode(ids) == "hello hello"
    assert 8 in tok.eos_token_ids
    r.close()


def test_embedded_unigram_tokenizer(tmp_path):
    path = tmp_path / "tok-uni.gguf"
    write_gguf(path, {"general.architecture": "llama", **_tok_metadata("llama")}, {})
    r = GGUFReader(path)
    tok = tokenizer_from_gguf(r)
    ids = tok.encode("hello world")
    assert ids == [3, 4]  # ▁hello ▁world win on score
    assert tok.decode(ids) == "hello world"
    r.close()


def test_control_tokens_skipped_on_decode(tmp_path):
    path = tmp_path / "tok-ctl.gguf"
    md = _tok_metadata("llama")
    # mark <s>/</s> as CONTROL (=3); rest NORMAL (=1)
    md["tokenizer.ggml.token_type"] = [2, 3, 3, 1, 1, 1, 1, 1, 1]
    write_gguf(path, {"general.architecture": "llama", **md}, {})
    r = GGUFReader(path)
    tok = tokenizer_from_gguf(r)
    r.close()
    assert tok.decode([3, 4, 2]) == "hello world"  # trailing </s> skipped
    assert "</s>" in tok.decode([3, 4, 2], skip_special_tokens=False)


def test_reader_closes_on_bad_file(tmp_path):
    bad = tmp_path / "bad.gguf"
    bad.write_bytes(b"NOPE" + b"\x00" * 64)
    with pytest.raises(ValueError, match="not a GGUF file"):
        GGUFReader(bad)


def test_rope_scaling_mapping(tmp_path):
    path = tmp_path / "rs.gguf"
    write_gguf(path, {
        "general.architecture": "llama",
        "llama.embedding_length": 64,
        "llama.block_count": 1,
        "llama.attention.head_count": 4,
        "llama.vocab_size": 16,
        "llama.rope.scaling.type": "llama3",
        "llama.rope.scaling.factor": 8.0,
        "llama.rope.scaling.original_context_length": 8192,
    }, {})
    r = GGUFReader(path)
    cfg = config_from_gguf(r)
    r.close()
    assert cfg.rope_scaling == {
        "rope_type": "llama3", "factor": 8.0,
        "original_max_position_embeddings": 8192,
        "low_freq_factor": 1.0, "high_freq_factor": 4.0,
    }


def test_yarn_scaling_survives_export_roundtrip(tmp_path):
    scaling = {"rope_type": "yarn", "factor": 4.0, "low_freq_factor": 1.0,
               "high_freq_factor": 4.0, "original_max_position_embeddings": 8192,
               "attention_factor": 1.5, "beta_fast": 24.0, "beta_slow": 2.0}
    cfg = dataclasses.replace(PRESETS["test-tiny"], rope_scaling=scaling)
    params = llama.init_params(cfg, 18)
    path = tmp_path / "yarn.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    r.close()
    assert cfg2.rope_scaling == scaling


def test_rope_scaling_survives_export_roundtrip(tmp_path):
    scaling = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
               "high_freq_factor": 4.0, "original_max_position_embeddings": 8192}
    cfg = dataclasses.replace(PRESETS["test-tiny"], rope_scaling=scaling)
    params = llama.init_params(cfg, 17)
    path = tmp_path / "scaled.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    r.close()
    assert cfg2.rope_scaling == scaling


def test_moe_shared_expert_roundtrip(tmp_path):
    cfg = dataclasses.replace(
        PRESETS["test-tiny-moe"], shared_expert_size=32, shared_expert_gated=True,
    )
    params = llama.init_params(cfg, 21)
    path = tmp_path / "moe.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    assert cfg2.num_experts == cfg.num_experts
    assert cfg2.num_experts_per_token == cfg.num_experts_per_token
    assert cfg2.shared_expert_size == cfg.shared_expert_size
    assert cfg2.shared_expert_gated
    loaded = load_gguf_params(r, cfg2, dtype="float32")
    r.close()

    import jax

    flat_a = jax.tree.leaves(jax.tree.map(np.asarray, params))
    flat_b = jax.tree.leaves(jax.tree.map(np.asarray, loaded))
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-6)


def test_config_and_params_roundtrip(tmp_path):
    cfg = dataclasses.replace(PRESETS["test-tiny"], tie_embeddings=False)
    params = llama.init_params(cfg, 11)
    path = tmp_path / "model.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    assert cfg2.hidden_size == cfg.hidden_size
    assert cfg2.num_layers == cfg.num_layers
    assert cfg2.num_kv_heads == cfg.num_kv_heads
    assert cfg2.head_dim == cfg.head_dim
    assert cfg2.intermediate_size == cfg.intermediate_size
    assert not cfg2.tie_embeddings  # output.weight present
    loaded = load_gguf_params(r, cfg2, dtype="float32")
    r.close()

    import jax

    host = jax.tree.map(np.asarray, params)
    flat_a = jax.tree.leaves(host)
    flat_b = jax.tree.leaves(jax.tree.map(np.asarray, loaded))
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-6)


def test_quantized_load_close(tmp_path):
    """Q8_0-stored weights come back within block-quant tolerance everywhere."""
    import jax

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 12)
    path = tmp_path / "model-q8.gguf"
    save_params_gguf(path, cfg, params, quant=GGML_Q8_0)
    r = GGUFReader(path)
    loaded = load_gguf_params(r, config_from_gguf(r, name=cfg.name), dtype="float32")
    r.close()

    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, params)),
                    jax.tree.leaves(jax.tree.map(np.asarray, loaded))):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(a32).max(), 1e-6)
        assert np.abs(a32 - b32).max() <= scale / 100.0  # int8 blocks: <1% of range


def test_worker_spec_from_gguf(tmp_path):
    from dynamo_tpu.launch import WorkerSpec

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 13)
    path = tmp_path / "served.gguf"
    save_params_gguf(path, cfg, params, tokenizer_metadata=_tok_metadata("gpt2"))
    spec = WorkerSpec.from_model_dir(str(path), name="tiny-gguf")
    assert spec.model_config.hidden_size == cfg.hidden_size
    assert spec.card.name == "tiny-gguf"
    assert spec.card.tokenizer.endswith(".gguf")
    from dynamo_tpu.tokenizer import load_tokenizer

    tok = load_tokenizer(spec.card.tokenizer)
    assert tok.decode(tok.encode("hello hello")) == "hello hello"
