"""GGUF support: binary round-trip, dequant correctness, config/tokenizer
extraction, params loading, and WorkerSpec resolution of a .gguf path.

The writer emits spec-conformant GGUF v3 (magic, typed metadata, reversed
ggml dims, aligned data section), so reading back through the parser proves
both directions against the format llama.cpp tools produce.
"""

import dataclasses
import struct

import numpy as np
import pytest

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.models.gguf import (
    GGML_F16,
    GGML_Q4_0,
    GGML_Q8_0,
    GGUFReader,
    config_from_gguf,
    load_gguf_params,
    save_params_gguf,
    tokenizer_from_gguf,
    write_gguf,
)


def test_metadata_roundtrip(tmp_path):
    path = tmp_path / "m.gguf"
    md = {
        "general.architecture": "llama",
        "llama.block_count": 2,
        "llama.rope.freq_base": 10000.0,
        "flag": True,
        "tokenizer.ggml.tokens": ["a", "b", "c"],
        "tokenizer.ggml.scores": [0.0, -1.0, -2.0],
        "ids": [3, 1, 2],
    }
    write_gguf(path, md, {"t": np.arange(64, dtype=np.float32).reshape(8, 8)})
    r = GGUFReader(path)
    assert r.version == 3
    assert r.metadata["general.architecture"] == "llama"
    assert r.metadata["llama.block_count"] == 2
    assert r.metadata["flag"] is True
    assert r.metadata["tokenizer.ggml.tokens"] == ["a", "b", "c"]
    assert r.metadata["ids"] == [3, 1, 2]
    np.testing.assert_allclose(r.metadata["tokenizer.ggml.scores"], [0.0, -1.0, -2.0])
    r.close()


def test_tensor_dtypes_roundtrip(tmp_path):
    import ml_dtypes

    path = tmp_path / "t.gguf"
    rng = np.random.default_rng(0)
    f32 = rng.standard_normal((4, 32)).astype(np.float32)
    f16 = rng.standard_normal((64,)).astype(np.float16)
    bf16 = rng.standard_normal((2, 3, 32)).astype(ml_dtypes.bfloat16)
    write_gguf(path, {"general.architecture": "llama"}, {"f32": f32, "f16": f16, "bf16": bf16})
    r = GGUFReader(path)
    np.testing.assert_array_equal(r.read("f32"), f32)
    np.testing.assert_array_equal(r.read("f16"), f16)
    np.testing.assert_array_equal(np.asarray(r.read("bf16"), np.float32), np.asarray(bf16, np.float32))
    # shapes come back in numpy orientation despite reversed on-disk dims
    assert r.tensors["bf16"].shape == (2, 3, 32)
    r.close()


def test_q8_0_quant_roundtrip(tmp_path):
    path = tmp_path / "q.gguf"
    rng = np.random.default_rng(1)
    w = rng.standard_normal((16, 64)).astype(np.float32)
    write_gguf(path, {"general.architecture": "llama"}, {"w": w}, quant=GGML_Q8_0)
    r = GGUFReader(path)
    got = r.read("w")
    # int8 block quant: max error bounded by half a quant step per block
    err = np.abs(got - w)
    step = np.abs(w).reshape(-1, 32).max(axis=1) / 127.0
    assert (err.reshape(-1, 32) <= step[:, None] * 0.51 + 1e-6).all()
    r.close()


def test_q4_0_dequant_against_formula(tmp_path):
    # Hand-build one Q4_0 block: d=0.5, qs nibbles 0..15 twice
    d = np.float16(0.5)
    qs = bytes((i | (i << 4)) for i in range(16))  # low nibble i (elem i), high nibble i (elem i+16)
    raw = struct.pack("<e", d) + qs
    from dynamo_tpu.models.gguf import _dequant

    got = _dequant(raw, GGML_Q4_0, (32,))
    expect = np.concatenate([np.arange(16), np.arange(16)]).astype(np.float32)
    expect = (expect - 8.0) * 0.5
    np.testing.assert_allclose(got, expect)


def test_q4_0_writer_roundtrip(tmp_path):
    path = tmp_path / "q4.gguf"
    rng = np.random.default_rng(3)
    w = rng.standard_normal((8, 64)).astype(np.float32)
    write_gguf(path, {"general.architecture": "llama"}, {"w": w}, quant=GGML_Q4_0)
    r = GGUFReader(path)
    got = r.read("w")
    r.close()
    # 4-bit blocks: quants land within half a step except at the positive
    # extreme, where the asymmetric [-8, 7] range costs up to one full step
    step = np.abs(w).reshape(-1, 32).max(axis=1) / 8.0
    assert (np.abs(got - w).reshape(-1, 32) <= step[:, None] * 1.01 + 1e-6).all()


def test_alignment_key_not_duplicated(tmp_path):
    path = tmp_path / "al.gguf"
    v = np.arange(32, dtype=np.float32)
    write_gguf(path, {"general.architecture": "llama", "general.alignment": 64}, {"v": v})
    r = GGUFReader(path)
    assert r.metadata["general.alignment"] == 64
    np.testing.assert_array_equal(r.read("v"), v)  # data laid out at 64 too
    r.close()


def test_mixed_int_float_array_promotes(tmp_path):
    path = tmp_path / "mix.gguf"
    write_gguf(path, {"general.architecture": "llama", "scores": [0, -1.25, -2.5]}, {})
    r = GGUFReader(path)
    np.testing.assert_allclose(r.metadata["scores"], [0.0, -1.25, -2.5])
    r.close()


def test_rope_scaling_linear_and_yarn():
    from dynamo_tpu.ops.rope import rope_frequencies

    base = rope_frequencies(64, theta=10000.0)
    lin = rope_frequencies(64, theta=10000.0, scaling={"rope_type": "linear", "factor": 4.0})
    np.testing.assert_allclose(lin, base / 4.0, rtol=1e-6)
    yarn = rope_frequencies(
        64, theta=10000.0,
        scaling={"rope_type": "yarn", "factor": 4.0, "original_max_position_embeddings": 4096},
    )
    # high-frequency dims extrapolate (unchanged), low-frequency interpolate
    np.testing.assert_allclose(yarn[0], base[0], rtol=1e-6)
    np.testing.assert_allclose(yarn[-1], base[-1] / 4.0, rtol=1e-6)
    assert ((yarn <= base + 1e-9) & (yarn >= base / 4.0 - 1e-9)).all()
    with pytest.raises(ValueError, match="unsupported rope scaling"):
        rope_frequencies(64, scaling={"rope_type": "longrope", "factor": 2.0})

    from dynamo_tpu.ops.rope import rope_attention_factor

    assert rope_attention_factor(None) == 1.0
    assert rope_attention_factor({"rope_type": "llama3", "factor": 8.0}) == 1.0
    yf = rope_attention_factor({"rope_type": "yarn", "factor": 4.0})
    np.testing.assert_allclose(yf, 0.1 * np.log(4.0) + 1.0)
    assert rope_attention_factor({"rope_type": "yarn", "factor": 4.0, "attention_factor": 1.5}) == 1.5


def test_unblockable_quant_falls_back(tmp_path):
    path = tmp_path / "fb.gguf"
    v = np.arange(7, dtype=np.float32)  # 7 % 32 != 0 -> cannot block-quantize
    write_gguf(path, {"general.architecture": "llama"}, {"v": v}, quant=GGML_Q8_0)
    r = GGUFReader(path)
    assert r.tensors["v"].ggml_type == GGML_F16
    np.testing.assert_allclose(r.read("v"), v)
    r.close()


def _tok_metadata(kind="gpt2"):
    if kind == "gpt2":
        # Byte-level BPE over a tiny vocab: enough to encode "hello hello"
        vocab = ["h", "e", "l", "o", "Ġ", "he", "ll", "hell", "hello", "Ġhello"]
        merges = ["h e", "l l", "he ll", "hell o", "Ġ hello"]
        return {
            "tokenizer.ggml.model": "gpt2",
            "tokenizer.ggml.tokens": vocab,
            "tokenizer.ggml.merges": merges,
            "tokenizer.ggml.bos_token_id": 8,
            "tokenizer.ggml.eos_token_id": 8,
        }
    # unigram ("llama"-style) with metaspace pieces
    tokens = ["<unk>", "<s>", "</s>", "▁hello", "▁world", "▁", "h", "w", "o"]
    scores = [0.0, 0.0, 0.0, -1.0, -1.5, -2.0, -3.0, -3.0, -3.0]
    return {
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": tokens,
        "tokenizer.ggml.scores": scores,
        "tokenizer.ggml.unknown_token_id": 0,
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
    }


def test_embedded_bpe_tokenizer(tmp_path):
    path = tmp_path / "tok.gguf"
    write_gguf(path, {"general.architecture": "llama", **_tok_metadata("gpt2")}, {})
    r = GGUFReader(path)
    tok = tokenizer_from_gguf(r)
    ids = tok.encode("hello hello")
    assert tok.decode(ids) == "hello hello"
    assert 8 in tok.eos_token_ids
    r.close()


def test_embedded_unigram_tokenizer(tmp_path):
    path = tmp_path / "tok-uni.gguf"
    write_gguf(path, {"general.architecture": "llama", **_tok_metadata("llama")}, {})
    r = GGUFReader(path)
    tok = tokenizer_from_gguf(r)
    ids = tok.encode("hello world")
    assert ids == [3, 4]  # ▁hello ▁world win on score
    assert tok.decode(ids) == "hello world"
    r.close()


def test_control_tokens_skipped_on_decode(tmp_path):
    path = tmp_path / "tok-ctl.gguf"
    md = _tok_metadata("llama")
    # mark <s>/</s> as CONTROL (=3); rest NORMAL (=1)
    md["tokenizer.ggml.token_type"] = [2, 3, 3, 1, 1, 1, 1, 1, 1]
    write_gguf(path, {"general.architecture": "llama", **md}, {})
    r = GGUFReader(path)
    tok = tokenizer_from_gguf(r)
    r.close()
    assert tok.decode([3, 4, 2]) == "hello world"  # trailing </s> skipped
    assert "</s>" in tok.decode([3, 4, 2], skip_special_tokens=False)


def test_reader_closes_on_bad_file(tmp_path):
    bad = tmp_path / "bad.gguf"
    bad.write_bytes(b"NOPE" + b"\x00" * 64)
    with pytest.raises(ValueError, match="not a GGUF file"):
        GGUFReader(bad)


def test_rope_scaling_mapping(tmp_path):
    path = tmp_path / "rs.gguf"
    write_gguf(path, {
        "general.architecture": "llama",
        "llama.embedding_length": 64,
        "llama.block_count": 1,
        "llama.attention.head_count": 4,
        "llama.vocab_size": 16,
        "llama.rope.scaling.type": "llama3",
        "llama.rope.scaling.factor": 8.0,
        "llama.rope.scaling.original_context_length": 8192,
    }, {})
    r = GGUFReader(path)
    cfg = config_from_gguf(r)
    r.close()
    assert cfg.rope_scaling == {
        "rope_type": "llama3", "factor": 8.0,
        "original_max_position_embeddings": 8192,
        "low_freq_factor": 1.0, "high_freq_factor": 4.0,
    }


def test_yarn_scaling_survives_export_roundtrip(tmp_path):
    scaling = {"rope_type": "yarn", "factor": 4.0, "low_freq_factor": 1.0,
               "high_freq_factor": 4.0, "original_max_position_embeddings": 8192,
               "attention_factor": 1.5, "beta_fast": 24.0, "beta_slow": 2.0}
    cfg = dataclasses.replace(PRESETS["test-tiny"], rope_scaling=scaling)
    params = llama.init_params(cfg, 18)
    path = tmp_path / "yarn.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    r.close()
    assert cfg2.rope_scaling == scaling


def test_rope_scaling_survives_export_roundtrip(tmp_path):
    scaling = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
               "high_freq_factor": 4.0, "original_max_position_embeddings": 8192}
    cfg = dataclasses.replace(PRESETS["test-tiny"], rope_scaling=scaling)
    params = llama.init_params(cfg, 17)
    path = tmp_path / "scaled.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    r.close()
    assert cfg2.rope_scaling == scaling


def test_moe_shared_expert_roundtrip(tmp_path):
    cfg = dataclasses.replace(
        PRESETS["test-tiny-moe"], shared_expert_size=32, shared_expert_gated=True,
    )
    params = llama.init_params(cfg, 21)
    path = tmp_path / "moe.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    assert cfg2.num_experts == cfg.num_experts
    assert cfg2.num_experts_per_token == cfg.num_experts_per_token
    assert cfg2.shared_expert_size == cfg.shared_expert_size
    assert cfg2.shared_expert_gated
    loaded = load_gguf_params(r, cfg2, dtype="float32")
    r.close()

    import jax

    flat_a = jax.tree.leaves(jax.tree.map(np.asarray, params))
    flat_b = jax.tree.leaves(jax.tree.map(np.asarray, loaded))
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-6)


def test_config_and_params_roundtrip(tmp_path):
    cfg = dataclasses.replace(PRESETS["test-tiny"], tie_embeddings=False)
    params = llama.init_params(cfg, 11)
    path = tmp_path / "model.gguf"
    save_params_gguf(path, cfg, params)
    r = GGUFReader(path)
    cfg2 = config_from_gguf(r, name=cfg.name)
    assert cfg2.hidden_size == cfg.hidden_size
    assert cfg2.num_layers == cfg.num_layers
    assert cfg2.num_kv_heads == cfg.num_kv_heads
    assert cfg2.head_dim == cfg.head_dim
    assert cfg2.intermediate_size == cfg.intermediate_size
    assert not cfg2.tie_embeddings  # output.weight present
    loaded = load_gguf_params(r, cfg2, dtype="float32")
    r.close()

    import jax

    host = jax.tree.map(np.asarray, params)
    flat_a = jax.tree.leaves(host)
    flat_b = jax.tree.leaves(jax.tree.map(np.asarray, loaded))
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-6)


def test_quantized_load_close(tmp_path):
    """Q8_0-stored weights come back within block-quant tolerance everywhere."""
    import jax

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 12)
    path = tmp_path / "model-q8.gguf"
    save_params_gguf(path, cfg, params, quant=GGML_Q8_0)
    r = GGUFReader(path)
    loaded = load_gguf_params(r, config_from_gguf(r, name=cfg.name), dtype="float32")
    r.close()

    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, params)),
                    jax.tree.leaves(jax.tree.map(np.asarray, loaded))):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(a32).max(), 1e-6)
        assert np.abs(a32 - b32).max() <= scale / 100.0  # int8 blocks: <1% of range


def test_q4_packed_read_matches_dequant(tmp_path):
    """read_q4 + nibble repack feeds models/quant.maybe_dequant the same
    values read()'s full dequant produces — Q4_0 bitwise (d*(q-8) is the
    native form), Q4_K within f32 rounding of the rewritten bias form."""
    import jax.numpy as jnp

    from dynamo_tpu.models.gguf import GGML_Q4_K, _pack_nibble_rows
    from dynamo_tpu.models.quant import maybe_dequant

    rng = np.random.default_rng(21)
    t40 = (rng.standard_normal((16, 64)) * 0.1).astype(np.float32)
    t4k = (rng.standard_normal((8, 256)) * 0.1).astype(np.float32)
    path = tmp_path / "q4pair.gguf"
    write_gguf(path, {"general.architecture": "llama"},
               {"a": t40, "b": t4k}, quant={"a": GGML_Q4_0, "b": GGML_Q4_K})
    r = GGUFReader(path)
    for name, exact in (("a", True), ("b", False)):
        dense = r.read(name)  # [out, in] f32 via the dequant path
        q, scale, bias = r.read_q4(name)
        leaf = {"qw4": _pack_nibble_rows(q.T), "scale": scale.T}
        if bias is not None:
            leaf["qbias"] = bias.T
        back = np.asarray(maybe_dequant(leaf, jnp.float32)).T
        if exact:
            np.testing.assert_array_equal(back, dense)
        else:
            np.testing.assert_allclose(back, dense, rtol=1e-6, atol=1e-7)
    r.close()


def test_q4_0_packed_model_load_matches_dequant_path(tmp_path):
    """``load_gguf_params(quantize="int4")`` imports Q4_0 matmul tensors as
    packed leaves whose dequant equals the full-width load BITWISE (the
    checkpoint's own codes and scales are repacked, not requantized); every
    other leaf comes back identical to the plain path."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.quant import is_quantized, maybe_dequant

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 17)
    path = tmp_path / "model-q4.gguf"
    save_params_gguf(path, cfg, params, quant=GGML_Q4_0)
    r = GGUFReader(path)
    mcfg = config_from_gguf(r, name=cfg.name)
    plain = load_gguf_params(r, mcfg, dtype="float32")
    packed = load_gguf_params(r, mcfg, dtype="float32", quantize="int4")
    r.close()
    for leaf in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        d = packed["layers"][leaf]
        assert is_quantized(d) and "qw4" in d, leaf
        np.testing.assert_array_equal(
            np.asarray(maybe_dequant(d, jnp.float32)),
            np.asarray(plain["layers"][leaf], np.float32), err_msg=leaf)
    for name in ("embed", "norm_f"):
        np.testing.assert_array_equal(np.asarray(packed[name]), np.asarray(plain[name]))


def test_worker_spec_from_gguf(tmp_path):
    from dynamo_tpu.launch import WorkerSpec

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 13)
    path = tmp_path / "served.gguf"
    save_params_gguf(path, cfg, params, tokenizer_metadata=_tok_metadata("gpt2"))
    spec = WorkerSpec.from_model_dir(str(path), name="tiny-gguf")
    assert spec.model_config.hidden_size == cfg.hidden_size
    assert spec.card.name == "tiny-gguf"
    assert spec.card.tokenizer.endswith(".gguf")
    from dynamo_tpu.tokenizer import load_tokenizer

    tok = load_tokenizer(spec.card.tokenizer)
    assert tok.decode(tok.encode("hello hello")) == "hello hello"


# ---------------------------------------------------------------------------
# K-quants: vectorized dequant vs literal transcriptions of ggml's loops
# ---------------------------------------------------------------------------


def _get_scale_min_k4(j, q):
    """ggml-common.h get_scale_min_k4, verbatim semantics."""
    if j < 4:
        return q[j] & 63, q[j + 4] & 63
    return (
        (q[j + 4] & 0xF) | ((q[j - 4] >> 6) << 4),
        (q[j + 4] >> 4) | ((q[j] >> 6) << 4),
    )


def _dequant_q4_k_scalar(block):
    import struct

    d, dmin = struct.unpack_from("<ee", block, 0)
    scales = block[4:16]
    qs = block[16:144]
    y = []
    q_off, is_ = 0, 0
    for _ in range(4):  # 64-element chunks
        sc1, m1 = _get_scale_min_k4(is_, scales)
        sc2, m2 = _get_scale_min_k4(is_ + 1, scales)
        for l in range(32):
            y.append(d * sc1 * (qs[q_off + l] & 0xF) - dmin * m1)
        for l in range(32):
            y.append(d * sc2 * (qs[q_off + l] >> 4) - dmin * m2)
        q_off += 32
        is_ += 2
    return np.asarray(y, np.float32)


def _dequant_q5_k_scalar(block):
    import struct

    d, dmin = struct.unpack_from("<ee", block, 0)
    scales = block[4:16]
    qh = block[16:48]
    qs = block[48:176]
    y = []
    q_off, is_, u1, u2 = 0, 0, 1, 2
    for _ in range(4):
        sc1, m1 = _get_scale_min_k4(is_, scales)
        sc2, m2 = _get_scale_min_k4(is_ + 1, scales)
        for l in range(32):
            y.append(d * sc1 * ((qs[q_off + l] & 0xF) + (16 if qh[l] & u1 else 0)) - dmin * m1)
        for l in range(32):
            y.append(d * sc2 * ((qs[q_off + l] >> 4) + (16 if qh[l] & u2 else 0)) - dmin * m2)
        q_off += 32
        is_ += 2
        u1 <<= 2
        u2 <<= 2
    return np.asarray(y, np.float32)


def _dequant_q6_k_scalar(block):
    import struct

    ql = block[0:128]
    qh = block[128:192]
    sc = np.frombuffer(block[192:208], np.int8)
    (d,) = struct.unpack_from("<e", block, 208)
    y = np.zeros(256, np.float32)
    for n in range(0, 256, 128):
        h = n // 128
        for l in range(32):
            is_ = l // 16
            q1 = ((ql[64 * h + l] & 0xF) | (((qh[32 * h + l] >> 0) & 3) << 4)) - 32
            q2 = ((ql[64 * h + l + 32] & 0xF) | (((qh[32 * h + l] >> 2) & 3) << 4)) - 32
            q3 = ((ql[64 * h + l] >> 4) | (((qh[32 * h + l] >> 4) & 3) << 4)) - 32
            q4 = ((ql[64 * h + l + 32] >> 4) | (((qh[32 * h + l] >> 6) & 3) << 4)) - 32
            y[n + l + 0] = d * sc[8 * h + is_ + 0] * q1
            y[n + l + 32] = d * sc[8 * h + is_ + 2] * q2
            y[n + l + 64] = d * sc[8 * h + is_ + 4] * q3
            y[n + l + 96] = d * sc[8 * h + is_ + 6] * q4
    return y


@pytest.mark.parametrize(
    "ggml_type,block_bytes,scalar",
    [
        (12, 144, _dequant_q4_k_scalar),   # Q4_K
        (13, 176, _dequant_q5_k_scalar),   # Q5_K
        (14, 210, _dequant_q6_k_scalar),   # Q6_K
    ],
)
def test_k_quant_dequant_matches_ggml_semantics(ggml_type, block_bytes, scalar):
    """Random block bytes (valid by construction: fp16 fields patched to
    finite values) dequantized by the vectorized loader must match a literal
    transcription of ggml's reference loops."""
    from dynamo_tpu.models.gguf import _dequant

    rng = np.random.default_rng(ggml_type)
    nb = 3
    raw = bytearray(rng.integers(0, 256, nb * block_bytes, dtype=np.uint8).tobytes())
    # Patch the fp16 scale fields to small finite values (random bit
    # patterns can be inf/nan which never occur in real checkpoints).
    import struct

    for i in range(nb):
        base = i * block_bytes
        if ggml_type in (12, 13):  # d, dmin lead the block
            struct.pack_into("<ee", raw, base, 0.01 * (i + 1), 0.002 * (i + 1))
        else:  # Q6_K: d is the last field
            struct.pack_into("<e", raw, base + 208, 0.01 * (i + 1))
    raw = bytes(raw)

    got = _dequant(raw, ggml_type, (nb, 256))
    want = np.stack([scalar(raw[i * block_bytes : (i + 1) * block_bytes]) for i in range(nb)])
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_k_quant_tensor_reads_through_reader(tmp_path):
    """A GGUF containing Q4_K and Q6_K tensors reads end-to-end through
    GGUFReader (header parse -> offsets -> block math -> shape), via the
    writer's raw-tensor passthrough."""
    import struct

    from dynamo_tpu.models.gguf import GGUFReader, _dequant, write_gguf

    rng = np.random.default_rng(0)
    rows, cols = 2, 256
    nb = rows * cols // 256

    def blocks(bpb, patch_off, fmt="<e"):
        raw = bytearray(rng.integers(0, 256, nb * bpb, dtype=np.uint8).tobytes())
        for i in range(nb):
            struct.pack_into(fmt, raw, i * bpb + patch_off, 0.05)
        return bytes(raw)

    q4k = blocks(144, 0, "<ee"[:2])
    q6k = blocks(210, 208)
    path = tmp_path / "kquant.gguf"
    write_gguf(
        path,
        {"general.architecture": "llama"},
        {"plain.weight": np.ones((2, 4), np.float32)},
        raw_tensors={
            "q4k.weight": ((rows, cols), 12, q4k),
            "q6k.weight": ((rows, cols), 14, q6k),
        },
    )
    r = GGUFReader(path)
    try:
        got4 = r.read("q4k.weight")
        got6 = r.read("q6k.weight")
        np.testing.assert_allclose(got4, _dequant(q4k, 12, (rows, cols)))
        np.testing.assert_allclose(got6, _dequant(q6k, 14, (rows, cols)))
        np.testing.assert_allclose(r.read("plain.weight"), np.ones((2, 4), np.float32))
    finally:
        r.close()
