"""Deployment plane: api-store CRUD, operator reconciliation (fake + real
process backend), k8s manifest rendering, fleet metrics exporter.

The api-store + operator integration test is the control-plane loop the
reference runs through kubectl -> apiserver -> controller: a REST create
lands in the store, the watch fires, the reconciler actuates and writes
status back.
"""

import asyncio
import json

import aiohttp
import pytest
import yaml

from dynamo_tpu.deploy.api_store import ApiStore
from dynamo_tpu.deploy.manifests import render_bundle, render_crd, render_deployment
from dynamo_tpu.deploy.objects import STORE_PREFIX, DeploymentPhase, GraphDeployment
from dynamo_tpu.deploy.operator import Operator, ProcessBackend
from dynamo_tpu.runtime.discovery import MemoryStore


class FakeBackend:
    def __init__(self, fail: bool = False):
        self.applied: list[GraphDeployment] = []
        self.deleted: list[str] = []
        self.fail = fail

    async def apply(self, dep):
        if self.fail:
            raise RuntimeError("no capacity")
        self.applied.append(dep)
        return {"Worker": 1}

    async def delete(self, name):
        self.deleted.append(name)

    async def close(self):
        pass


async def _wait(op: Operator, pred, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        op.reconciled.clear()
        if await pred():
            return
        try:
            await asyncio.wait_for(op.reconciled.wait(), 0.5)
        except asyncio.TimeoutError:
            pass
    raise AssertionError("condition not reached")


async def test_api_store_crud():
    store = MemoryStore()
    api = await ApiStore(store).start()
    try:
        base = f"http://127.0.0.1:{api.port}/api/v1/deployments"
        async with aiohttp.ClientSession() as s:
            r = await s.post(base, json={"name": "a", "graph": "m:S", "labels": {"env": "prod"}})
            assert r.status == 201
            assert (await s.post(base, json={"name": "a", "graph": "m:S"})).status == 409
            assert (await s.post(base, json={"name": "x"})).status == 400
            await s.post(base, json={"name": "b", "graph": "m:T"})
            items = (await (await s.get(base)).json())["items"]
            assert [d["name"] for d in items] == ["a", "b"]
            filtered = (await (await s.get(base + "?label=env=prod")).json())["items"]
            assert [d["name"] for d in filtered] == ["a"]
            one = await (await s.get(base + "/a")).json()
            assert one["graph"] == "m:S" and one["generation"] == 1
            r = await s.put(base + "/a", json={"config": {"Worker": {"replicas": 2}}})
            assert (await r.json())["generation"] == 2
            assert (await s.get(base + "/missing")).status == 404
            assert (await s.delete(base + "/a")).status == 202
            # two-phase: record still present, phase deleting
            assert (await (await s.get(base + "/a")).json())["phase"] == "deleting"
    finally:
        await api.close()


async def test_operator_reconcile_lifecycle():
    store = MemoryStore()
    backend = FakeBackend()
    op = await Operator(store, backend, resync_seconds=999).start()
    try:
        dep = GraphDeployment(name="d1", graph="m:S")
        await store.put(dep.key, dep.to_bytes())

        async def running():
            raw = await store.get(dep.key)
            return raw and GraphDeployment.from_bytes(raw).phase == "running"

        await _wait(op, running)
        cur = GraphDeployment.from_bytes(await store.get(dep.key))
        assert cur.observed_generation == 1 and cur.services_ready == {"Worker": 1}
        assert len(backend.applied) == 1

        # status echo must not re-apply
        await asyncio.sleep(0.3)
        assert len(backend.applied) == 1

        # spec bump -> re-apply
        cur.generation = 2
        cur.config = {"Worker": {"replicas": 3}}
        cur.phase = DeploymentPhase.PENDING.value
        await store.put(cur.key, cur.to_bytes())
        await _wait(op, lambda: _is(store, "d1", observed_generation=2))
        assert len(backend.applied) == 2

        # delete -> backend teardown + record removal
        cur = GraphDeployment.from_bytes(await store.get(dep.key))
        cur.phase = DeploymentPhase.DELETING.value
        await store.put(cur.key, cur.to_bytes())
        await _wait(op, lambda: _gone(store, "d1"))
        assert backend.deleted == ["d1"]
    finally:
        await op.close()


def _is(store, name, **fields):
    async def check():
        raw = await store.get(STORE_PREFIX + name)
        if raw is None:
            return False
        dep = GraphDeployment.from_bytes(raw)
        return all(getattr(dep, k) == v for k, v in fields.items())

    return check()


def _gone(store, name):
    async def check():
        return await store.get(STORE_PREFIX + name) is None

    return check()


async def test_operator_failure_surfaces_in_status():
    store = MemoryStore()
    op = await Operator(store, FakeBackend(fail=True), resync_seconds=999).start()
    try:
        dep = GraphDeployment(name="bad", graph="m:S")
        await store.put(dep.key, dep.to_bytes())
        await _wait(op, lambda: _is(store, "bad", phase="failed"))
        cur = GraphDeployment.from_bytes(await store.get(dep.key))
        assert "no capacity" in cur.message
        assert cur.observed_generation == 1  # no hot reconcile loop
    finally:
        await op.close()


async def test_update_during_delete_rejected():
    store = MemoryStore()
    api = await ApiStore(store).start()
    try:
        base = f"http://127.0.0.1:{api.port}/api/v1/deployments"
        async with aiohttp.ClientSession() as s:
            await s.post(base, json={"name": "d", "graph": "m:S"})
            await s.delete(base + "/d")
            r = await s.put(base + "/d", json={"graph": "m:T"})
            assert r.status == 409
    finally:
        await api.close()


async def test_operator_restart_recreates_running_fleet():
    """A RUNNING record whose workload the (new) backend doesn't hold must be
    re-applied on the start/resync pass — the operator-restart case."""

    class TrackingBackend(FakeBackend):
        def has(self, name):
            return any(d.name == name for d in self.applied)

    store = MemoryStore()
    dep = GraphDeployment(name="old", graph="m:S", phase="running", observed_generation=1)
    await store.put(dep.key, dep.to_bytes())
    backend = TrackingBackend()
    op = await Operator(store, backend, resync_seconds=999).start()
    try:
        await _wait(op, lambda: _is(store, "old", phase="running"))
        assert len(backend.applied) == 1  # re-created despite RUNNING status
        # …and the status echo does not apply again (has() now True)
        await asyncio.sleep(0.3)
        assert len(backend.applied) == 1
    finally:
        await op.close()


async def test_api_store_to_operator_integration():
    """REST create -> watch -> reconcile -> status visible over REST."""
    store = MemoryStore()
    api = await ApiStore(store).start()
    op = await Operator(store, FakeBackend(), resync_seconds=999).start()
    try:
        base = f"http://127.0.0.1:{api.port}/api/v1/deployments"
        async with aiohttp.ClientSession() as s:
            await s.post(base, json={"name": "live", "graph": "m:S"})
            await _wait(op, lambda: _is(store, "live", phase="running"))
            got = await (await s.get(base + "/live")).json()
            assert got["phase"] == "running"
            assert got["services_ready"] == {"Worker": 1}
            await s.delete(base + "/live")
            await _wait(op, lambda: _gone(store, "live"))
    finally:
        await op.close()
        await api.close()


async def test_planner_deployment_connector_scales_through_operator():
    """Planner decision -> deployment spec edit -> operator reconcile:
    the kubernetes-connector control loop on the local backend."""
    from dynamo_tpu.planner.connector import DeploymentConnector
    from dynamo_tpu.planner.core import PlanDecision

    store = MemoryStore()
    backend = FakeBackend()
    op = await Operator(store, backend, resync_seconds=999).start()
    try:
        dep = GraphDeployment(
            name="svc", graph="m:S", config={"Worker": {"replicas": 1}}
        )
        await store.put(dep.key, dep.to_bytes())
        await _wait(op, lambda: _is(store, "svc", phase="running"))
        base_applies = len(backend.applied)

        conn = DeploymentConnector(store, "svc", decode_service="Worker", prefill_service="Prefill")
        await conn.apply(PlanDecision(decode_workers=3, prefill_workers=1,
                                      predicted_prefill_tps=0, predicted_decode_tps=0))
        await _wait(op, lambda: _is(store, "svc", observed_generation=2, phase="running"))
        cur = GraphDeployment.from_bytes(await store.get(dep.key))
        assert cur.config["Worker"]["replicas"] == 3
        assert cur.config["Prefill"]["replicas"] == 1
        assert len(backend.applied) == base_applies + 1
        assert conn.scale_events == 1

        # identical decision -> no spec churn, no re-reconcile
        await conn.apply(PlanDecision(decode_workers=3, prefill_workers=1,
                                      predicted_prefill_tps=0, predicted_decode_tps=0))
        assert conn.scale_events == 1
        assert GraphDeployment.from_bytes(await store.get(dep.key)).generation == 2
    finally:
        await op.close()


async def test_process_backend_end_to_end(tmp_path):
    """A real deployment: operator spawns fleet subprocesses for the mock
    LLM graph and tears them down on delete."""
    store = MemoryStore()
    backend = ProcessBackend()
    op = await Operator(store, backend, resync_seconds=999).start()
    try:
        dep = GraphDeployment(
            name="fleet",
            graph="dynamo_tpu.sdk.graphs:Frontend",
            config={"Worker": {"mock": True, "model": "test-tiny"}},
        )
        await store.put(dep.key, dep.to_bytes())
        await _wait(op, lambda: _is(store, "fleet", phase="running"), timeout=30)
        fleet = backend.fleets["fleet"]
        assert len(fleet.procs) == 3  # Worker, Processor, Frontend
        assert all(entry[2].poll() is None for entry in fleet.procs)

        # the deployment actually serves: reach the Worker through the
        # fleet's own store/transport and run one request
        from dynamo_tpu.runtime.component import DistributedRuntime
        from dynamo_tpu.runtime.store_server import StoreClient
        from dynamo_tpu.runtime.tcp import TcpTransport

        rt = DistributedRuntime(
            StoreClient.from_url(f"tcp://127.0.0.1:{fleet.store_port}"), TcpTransport()
        )
        client = await (
            rt.namespace("inference").component("worker").endpoint("generate").client().start()
        )
        for _ in range(150):
            if client.instance_ids():
                break
            await asyncio.sleep(0.2)
        assert client.instance_ids()
        outs = [
            o async for o in client.generate(
                {"token_ids": [1, 2], "sampling": {}, "stop": {"max_tokens": 2}}
            )
        ]
        assert outs
        await client.close()
        await rt.close()
        cur = GraphDeployment.from_bytes(await store.get(dep.key))
        cur.phase = DeploymentPhase.DELETING.value
        await store.put(cur.key, cur.to_bytes())
        await _wait(op, lambda: _gone(store, "fleet"), timeout=30)
        assert "fleet" not in backend.fleets
    finally:
        await op.close()


def test_manifest_rendering():
    dep = GraphDeployment(
        name="agg",
        graph="dynamo_tpu.sdk.graphs:Frontend",
        config={"Worker": {"replicas": 4}, "Frontend": {"http_port": 8000}},
    )
    from dynamo_tpu.sdk.graph import load_graph

    graph = load_graph(dep.graph)
    docs = render_deployment(dep, graph)
    kinds = [(d["kind"], d["metadata"]["name"]) for d in docs]
    assert ("ConfigMap", "agg-config") in kinds
    assert ("Deployment", "agg-store") in kinds
    assert ("Deployment", "agg-worker") in kinds
    assert ("Service", "agg-frontend") in kinds

    by_name = {d["metadata"]["name"]: d for d in docs if d["kind"] == "Deployment"}
    worker = by_name["agg-worker"]
    assert worker["spec"]["replicas"] == 4
    container = worker["spec"]["template"]["spec"]["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == 1  # from @service resources
    assert "--service" in container["command"] and "Worker" in container["command"]
    cm = next(d for d in docs if d["kind"] == "ConfigMap")
    assert json.loads(cm["data"]["services.json"])["Worker"]["replicas"] == 4

    # bundle round-trips through a YAML parser; CRD parses too
    parsed = list(yaml.safe_load_all(render_bundle(dep, graph)))
    assert len(parsed) == len(docs)
    crd = yaml.safe_load(render_crd())
    assert crd["spec"]["names"]["kind"] == "GraphDeployment"


def test_helm_chart_renders_and_templates(tmp_path):
    """The generated chart, run through a helm-template stand-in, must
    reproduce exactly the operator's manifests (same renderer, values
    substituted back) and pass the apply-path validation."""
    from dynamo_tpu.deploy.helm import (
        render_helm_chart,
        simulate_helm_template,
        write_chart,
    )
    from dynamo_tpu.deploy.kubernetes import validate_manifest
    from dynamo_tpu.deploy.manifests import render_deployment
    from dynamo_tpu.deploy.objects import GraphDeployment
    from dynamo_tpu.sdk.graph import load_graph

    dep = GraphDeployment(
        name="agg", graph="dynamo_tpu.sdk.graphs:Frontend",
        config={"Worker": {"replicas": 3}, "Frontend": {"http_port": 8000}},
    )
    graph = load_graph(dep.graph)
    files = render_helm_chart(dep, graph, image="example.com/dynamo:v1")
    assert {"Chart.yaml", "values.yaml"} <= set(files)
    chart = yaml.safe_load(files["Chart.yaml"])
    assert chart["apiVersion"] == "v2" and chart["name"] == "agg"
    values = yaml.safe_load(files["values.yaml"])
    assert values["image"] == "example.com/dynamo:v1"
    assert values["services"]["worker"]["replicas"] == 3
    # Templates carry UNQUOTED Go-template expressions (quoted replicas
    # would render as strings and be rejected by the API server).
    tpl = files["templates/deployments.yaml"]
    assert "replicas: {{ int .Values.services.worker.replicas }}" in tpl
    assert "'{{" not in tpl

    rendered = simulate_helm_template(files)
    want = render_deployment(dep, graph, image="example.com/dynamo:v1")
    key = lambda d: (d["kind"], d["metadata"]["name"])  # noqa: E731
    assert sorted(map(key, rendered)) == sorted(map(key, want))
    for doc in rendered:
        validate_manifest(doc)
    by_key = {key(d): d for d in rendered}
    assert by_key[("Deployment", "agg-worker")]["spec"]["replicas"] == 3

    write_chart(files, str(tmp_path / "chart"))
    assert (tmp_path / "chart" / "templates" / "deployments.yaml").exists()


def test_gateway_assets_render():
    from dynamo_tpu.deploy.helm import render_gateway
    from dynamo_tpu.deploy.objects import GraphDeployment
    from dynamo_tpu.sdk.graph import load_graph

    dep = GraphDeployment(
        name="agg", graph="dynamo_tpu.sdk.graphs:Frontend",
        config={"Frontend": {"http_port": 8000}},
    )
    docs = render_gateway(dep, load_graph(dep.graph), models=["llama-3-8b"])
    kinds = {d["kind"]: d for d in docs}
    assert set(kinds) == {"Gateway", "HTTPRoute", "InferencePool", "InferenceModel"}
    route = kinds["HTTPRoute"]["spec"]["rules"][0]
    assert route["backendRefs"][0] == {"name": "agg-frontend", "port": 8000}
    assert kinds["InferencePool"]["spec"]["targetPortNumber"] == 8000
    assert kinds["InferenceModel"]["spec"]["modelName"] == "llama-3-8b"
    # No frontend -> explicit error, not an empty bundle.
    import pytest as _pytest

    with _pytest.raises(ValueError, match="http_port"):
        render_gateway(GraphDeployment(name="x", graph=dep.graph, config={}),
                       load_graph(dep.graph))


async def test_metrics_service_exports_worker_plane():
    from dynamo_tpu.deploy.metrics_service import MetricsService
    from dynamo_tpu.protocols.kv import ForwardPassMetrics
    from dynamo_tpu.router.metrics import metrics_key
    from dynamo_tpu.runtime.component import DistributedRuntime

    runtime = DistributedRuntime.detached()
    m = ForwardPassMetrics(
        worker_id=0xAB, kv_active_blocks=10, kv_total_blocks=40,
        num_requests_running=2, generated_tokens_total=123,
    )
    await runtime.store.put(
        metrics_key("dynamo", "backend", 0xAB), json.dumps(m.to_dict()).encode()
    )
    svc = await MetricsService(runtime).start()
    try:
        async with aiohttp.ClientSession() as s:
            text = await (await s.get(f"http://127.0.0.1:{svc.port}/metrics")).text()
        assert 'dynamo_worker_generated_tokens_total{worker_id="ab"} 123' in text
        assert 'dynamo_worker_cache_usage{worker_id="ab"} 0.250000' in text
        assert "dynamo_worker_up 1" in text
        health = json.loads(
            await (await aiohttp.ClientSession().get(f"http://127.0.0.1:{svc.port}/healthz")).text()
        )
        assert health["workers"] == 1
    finally:
        await svc.close()
        await runtime.close()
