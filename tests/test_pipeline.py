"""Pipeline graph: operator composition, segment split across the runtime,
context propagation through a cut edge.

The split test is the reference's SegmentSource/SegmentSink scenario
(`pipeline/nodes/sinks/segment.rs`): one logical pipeline, head in the
"frontend process", tail served as an endpoint, identical behavior to the
unsplit build.
"""

import asyncio
from typing import Any, AsyncIterator

import pytest

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.engine import AsyncEngine, Context, collect
from dynamo_tpu.runtime.pipeline import (
    FnOperator,
    Pipeline,
    PipelineError,
    SegmentSink,
    segment_client,
    serve_segment,
)


class EchoBackend(AsyncEngine[Any, Any]):
    """Streams each item of the request list, observing the context."""

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        for item in request["items"]:
            if context.is_stopped or context.is_killed:
                return
            await asyncio.sleep(0)
            yield {"value": item}


def double_req(req):
    return {"items": [x * 2 for x in req["items"]]}


def add_tag(item):
    return {**item, "tag": True}


async def test_build_composes_in_order():
    pipe = Pipeline().link(FnOperator.factory(on_request=double_req)).link(
        FnOperator.factory(on_item=add_tag)
    )
    engine = pipe.build(EchoBackend())
    out = await collect(engine.generate({"items": [1, 2, 3]}, Context()))
    assert out == [{"value": 2, "tag": True}, {"value": 4, "tag": True}, {"value": 6, "tag": True}]


async def test_split_equivalence_over_network():
    pipe = Pipeline(
        [FnOperator.factory(on_request=double_req), FnOperator.factory(on_item=add_tag)]
    )
    whole = pipe.build(EchoBackend())
    expect = await collect(whole.generate({"items": [5, 7]}, Context()))

    head, tail, sink = pipe.split(1)
    runtime = DistributedRuntime.detached()
    try:
        ep = runtime.namespace("t").component("seg").endpoint("run")
        await serve_segment(ep, tail, EchoBackend())
        client = await ep.client().start()
        sink.attach(segment_client(client))
        front = head.build(sink)
        got = await collect(front.generate({"items": [5, 7]}, Context()))
        assert got == expect
        await client.close()
    finally:
        await runtime.close()


async def test_sink_unattached_fails_loudly():
    _head, _tail, sink = Pipeline([FnOperator.factory()]).split(1)
    with pytest.raises(PipelineError, match="not attached"):
        await collect(sink.generate({}, Context()))
    sink.attach(EchoBackend())
    with pytest.raises(PipelineError, match="already attached"):
        sink.attach(EchoBackend())


async def test_split_bounds_checked():
    with pytest.raises(PipelineError, match="split point"):
        Pipeline([FnOperator.factory()]).split(5)


async def test_stop_propagates_through_segment():
    runtime = DistributedRuntime.detached()
    try:
        ep = runtime.namespace("t").component("seg2").endpoint("run")
        await serve_segment(ep, Pipeline(), EchoBackend())
        client = await ep.client().start()
        sink = SegmentSink()
        sink.attach(segment_client(client))
        ctx = Context()
        stream = sink.generate({"items": list(range(1000))}, ctx)
        got = []
        async for item in stream:
            got.append(item)
            if len(got) == 3:
                ctx.stop_generating()
        assert 3 <= len(got) < 1000
        await client.close()
    finally:
        await runtime.close()
