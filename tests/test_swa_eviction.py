"""Sliding-window page eviction: out-of-window KV pages are released while
the sequence keeps decoding, without changing a single output token.

A window-w model can never attend keys at positions <= q_pos - w, so pages
wholly below the window are dead weight (a 32k-context Mistral stream with
window 4k pins ~28k tokens of KV otherwise). Release must be invisible:
the block table keeps positional shape via the null page, whose (masked)
contents can't influence logits.
"""

import dataclasses

import numpy as np

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.runtime.engine import Context

PAGE = 4
CFG = dataclasses.replace(PRESETS["test-tiny"], sliding_window=8)  # 2 pages of window
PARAMS = llama.init_params(CFG, 0)


def _core(swa_free: bool, num_pages=64, caching=True):
    runner = ModelRunner(CFG, PARAMS, num_pages=num_pages, page_size=PAGE,
                         max_batch_size=2, prefill_bucket=16, attn_impl="reference")
    return EngineCore(runner, EngineConfig(
        num_pages=num_pages, page_size=PAGE, max_batch_size=2,
        max_prefill_tokens=64, max_seq_len=128, decode_steps=2,
        swa_free_pages=swa_free, enable_prefix_caching=caching,
    ))


def _generate(core, n_gen=40, prompt=(3, 5, 7, 11, 13, 2, 4, 6)):
    seq = core.add_request(PreprocessedRequest(
        token_ids=list(prompt), sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n_gen, ignore_eos=True),
    ), Context())
    toks = []
    live = []
    zeros = []
    while core.has_work:
        for s, out in core.step():
            toks.extend(out.token_ids)
        if seq.pages:  # cleared at finish
            live.append(sum(1 for p in seq.pages if p != 0))
            zeros.append(seq.pages.count(0))
    return toks, seq, (live, zeros)


def test_out_of_window_pages_release_without_changing_tokens():
    base_toks, _s, (base_live, base_zeros) = _generate(_core(swa_free=False))
    toks, _s2, (live, zeros) = _generate(_core(swa_free=True))
    assert toks == base_toks, "page release changed generated tokens"
    # Pages below the window were nulled out of the table during the run...
    assert max(zeros) > 0
    # ...bounded by the window: live pages stay at window + partial + slack
    # while the non-freeing run's footprint keeps growing.
    window_pages = CFG.sliding_window // PAGE
    assert live[-1] <= window_pages + 2
    assert base_live[-1] > live[-1]
    assert max(base_zeros) == 0


def test_stream_longer_than_the_pool_without_caching():
    """With prefix caching off, released pages go straight to the free
    list: a stream whose total context EXCEEDS the pool (48 tokens = 12
    pages vs 9 usable) completes with zero preemptions — impossible
    without the release."""
    core = _core(swa_free=True, num_pages=10, caching=False)
    toks, _seq, (live, _zeros) = _generate(core, n_gen=40)
    assert len(toks) == 40
    assert core.num_preemptions == 0
    assert max(live) <= 10  # never holds anywhere near 12 pages
    # Control: the same run without the release cannot fit the pool.
    ctrl = _core(swa_free=False, num_pages=10, caching=False)
    ctrl_toks, _s, _ = _generate(ctrl, n_gen=40)
    assert ctrl.num_preemptions > 0 or len(ctrl_toks) < 40


def test_released_pages_evictable_while_stream_still_running():
    """With caching on, released pages demote to refcount-0 prefix cache
    that a CONCURRENT request can evict — the long stream keeps decoding,
    nobody is preempted. Without the release those pages stay pinned by
    the running sequence and admission must preempt it."""
    def drive(swa_free):
        core = _core(swa_free=swa_free, num_pages=14)
        long_req = PreprocessedRequest(
            token_ids=[3, 5, 7, 11, 13, 2, 4, 6],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=40, ignore_eos=True),
        )
        core.add_request(long_req, Context())
        for _ in range(12):  # long stream slides well past its window
            core.step()
        # Second request: needs more pages than the free list holds.
        core.add_request(PreprocessedRequest(
            token_ids=list(range(20, 36)),  # 4 pages of prompt
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
        ), Context())
        done = 0
        while core.has_work and done < 200:
            core.step()
            done += 1
        return core

    core = drive(swa_free=True)
    assert core.num_preemptions == 0, "demoted pages should satisfy admission"
    ctrl = drive(swa_free=False)
    assert ctrl.num_preemptions > 0, "control must actually be page-starved"
