"""Wire v3 (striped multi-stream KV transfer): byte-exactness vs v2,
out-of-order reassembly, per-stripe crc retry, rollback drills, staging
budget, and the blob frame codec.

The v2 contract these tests hold v3 to (docs/KV_TRANSFER_WIRE_V2.md): every
committed prefix is a valid cache state, a crc failure retries the same seq
before anything rolls back, and a dead stream leaves no pins and no session.
"""

import asyncio
import time

import numpy as np
import pytest

from dynamo_tpu.disagg.transfer import (
    KvTransferService,
    block_crc_ok,
    blob_to_blocks,
    default_chunk_pages,
    default_wire_streams,
    pack_chunk_blob,
    send_blocks_chunked,
    unpack_payload,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.transport import (
    DuplexUnsupportedError,
    InMemoryTransport,
)
from dynamo_tpu.tokens import compute_block_hashes
from tests.test_transfer_pipeline import CFG, PAGE, _commit_chain, _core


@pytest.fixture(autouse=True)
def _fault_hygiene():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def _assert_chain_bytes(core, hashes, payloads):
    pids = core.allocator.match_prefix(hashes)
    assert len(pids) == len(hashes)
    for pid, h in zip(pids, hashes):
        k_got, v_got = core.runner.read_page(pid)
        np.testing.assert_array_equal(k_got, payloads[h][0])
        np.testing.assert_array_equal(v_got, payloads[h][1])
    core.allocator.release(pids)


def _chunk_msgs(hashes, payloads, chunk_pages=2):
    """Build v3 chunk messages (meta head + joined blob) for driving the
    receiver's duplex plane directly."""
    parents = [None, *hashes[:-1]]
    msgs = []
    n = -(-len(hashes) // chunk_pages)
    for i in range(n):
        sl = slice(i * chunk_pages, (i + 1) * chunk_pages)
        meta, bufs, _ = pack_chunk_blob(
            hashes[sl], parents[sl], [payloads[h] for h in hashes[sl]])
        msgs.append(({"seq": i, "blocks": meta, "last": i == n - 1}, bufs))
    return msgs


def _open_req(request_id, *, sid="sid-1", stripe=0, stripes=2, total_chunks=2):
    return {"request_id": request_id, "stream_open": True, "sid": sid,
            "stripe": stripe, "stripes": stripes, "total_chunks": total_chunks}


# -- end-to-end: striped sender against the real service ---------------------


async def test_striped_byte_exact_with_v2():
    """The same chain shipped striped (v3) and single-stream (v2) lands
    byte-identical on both receivers, with chain linkage intact and no
    session state, staging bytes, or stripe connections left behind."""
    src = _core(num_pages=32)
    hashes = compute_block_hashes(list(range(6 * PAGE)), PAGE, salt=0)
    payloads = _commit_chain(src, hashes)

    transport = InMemoryTransport()
    dst_v3, dst_v2 = _core(num_pages=32), _core(num_pages=32)
    svc_v3, svc_v2 = KvTransferService(dst_v3), KvTransferService(dst_v2)
    await transport.register_engine("kv_v3", svc_v3)
    await transport.register_engine("kv_v2", svc_v2)

    out = await send_blocks_chunked(
        transport, "mem://kv_v3", "r1", src, hashes, chunk_pages=2, streams=3)
    assert out["protocol"] == "v3" and out["streams"] == 3
    assert out["injected"] == 6 and out["total"] == 6 and out["last"]
    assert out["bytes"] == sum(k.nbytes + v.nbytes for k, v in payloads.values())
    assert set(out["phases"]) == {"gather_s", "pack_s", "wire_s"}

    out_v2 = await send_blocks_chunked(
        transport, "mem://kv_v2", "r1", src, hashes, chunk_pages=2, streams=0)
    assert "protocol" not in out_v2  # legacy path taken
    assert out_v2["injected"] == 6
    assert out["bytes"] == out_v2["bytes"]  # identical payload accounting

    for core in (dst_v3, dst_v2):
        _assert_chain_bytes(core, hashes, payloads)
    stats = svc_v3.stats()
    assert stats["streams_in_flight"] == 0
    assert stats["wire_conns"] == 0
    assert stats["staged_bytes"] == 0
    assert stats["paths"]["host_striped"]["transfers"] == 1
    assert stats["paths"]["host_striped"]["bytes"] == out["bytes"]
    assert svc_v2.stats()["paths"]["host_chunked"]["transfers"] == 1
    # Sender released its chain refcounts both times.
    again = src.allocator.match_prefix(hashes)
    assert len(again) == 6
    src.allocator.release(again)


async def test_striped_phase_accounting_is_wall_time():
    """wire_s/pack_s on the striped path are busy-interval unions across
    stripes — per-stream-attributed wall time, never a sum over concurrent
    streams — so no phase can exceed the end-to-end elapsed time."""
    src = _core(num_pages=32)
    hashes = compute_block_hashes(list(range(8 * PAGE)), PAGE, salt=0)
    _commit_chain(src, hashes)
    transport = InMemoryTransport()
    svc = KvTransferService(_core(num_pages=32))
    await transport.register_engine("kv", svc)

    t0 = time.perf_counter()
    out = await send_blocks_chunked(
        transport, "mem://kv", "r", src, hashes, chunk_pages=1, streams=4)
    elapsed = time.perf_counter() - t0
    assert out["streams"] == 4
    eps = 0.05  # clock skew headroom, generous for CI
    for phase, secs in out["phases"].items():
        assert secs <= elapsed + eps, (
            f"{phase}={secs} exceeds elapsed {elapsed}: summed across stripes?")


async def test_striped_single_stripe_corrupt_retries_before_rollback():
    """kv.chunk.send:corrupt@1 mangles one stripe's chunk; the receiver's
    crc check rejects it without touching the session, THAT stripe retries
    its seq with the clean buffers, and the stream completes byte-exact with
    zero rollbacks — v2's retry-before-rollback contract, per stripe."""
    src = _core(num_pages=32)
    hashes = compute_block_hashes(list(range(6 * PAGE)), PAGE, salt=0)
    payloads = _commit_chain(src, hashes)
    transport = InMemoryTransport()
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    await transport.register_engine("kv", svc)

    FAULTS.arm("kv.chunk.send:corrupt@1")
    out = await send_blocks_chunked(
        transport, "mem://kv", "r", src, hashes, chunk_pages=2, streams=3)
    assert out["protocol"] == "v3"
    assert out["injected"] == 6 and out["crc_retries"] == 1
    assert svc.crc_failures == 1 and svc.rollbacks == 0
    _assert_chain_bytes(dst, hashes, payloads)


async def test_striped_stripe_loss_rolls_back_and_sender_raises():
    """A receiver-side failure on one stripe rolls the whole session back:
    the sender raises (its caller falls back to v1), pins drop, and the
    decode worker keeps at most a valid evictable prefix."""
    src = _core(num_pages=32)
    hashes = compute_block_hashes(list(range(6 * PAGE)), PAGE, salt=0)
    _commit_chain(src, hashes)
    transport = InMemoryTransport()
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    await transport.register_engine("kv", svc)

    FAULTS.arm("kv.chunk.recv:drop@2")  # one stripe's arrival dies
    with pytest.raises(Exception):
        await send_blocks_chunked(
            transport, "mem://kv", "r", src, hashes, chunk_pages=2, streams=3)
    assert svc.rollbacks == 1
    committed = dst.allocator.match_prefix(hashes)
    assert len(committed) < 6
    dst.allocator.release(committed)
    stats = svc.stats()
    assert stats["streams_in_flight"] == 0
    assert stats["staged_bytes"] == 0
    assert stats["wire_conns"] == 0
    # Nothing left pinned: eviction can reclaim everything.
    free0 = dst.allocator.num_free()
    dst.allocator.clear_cache()
    assert dst.allocator.num_free() >= free0


# -- receiver duplex plane driven directly ------------------------------------


async def test_out_of_order_reassembly_commits_in_seq_order():
    """Chunks arriving out of order stage and commit strictly in seq order:
    the ahead-of-cursor stripe's ack is deferred until its chunk commits,
    and the final ack carries the stream summary."""
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    transport = InMemoryTransport()
    await transport.register_engine("kv", svc)
    hashes = compute_block_hashes(list(range(4 * PAGE)), PAGE, salt=0)
    src = _core(num_pages=32)
    payloads = _commit_chain(src, hashes)
    msgs = _chunk_msgs(hashes, payloads, chunk_pages=2)

    st0 = await transport.open_duplex("mem://kv", _open_req("r", stripe=0), Context())
    st1 = await transport.open_duplex("mem://kv", _open_req("r", stripe=1), Context())
    try:
        # Stripe 1 delivers the LAST chunk first: it stages, no ack yet.
        fields, bufs = msgs[1]
        await st1.send({"request_id": "r", **fields}, blobs=bufs)
        ack1_task = asyncio.create_task(st1.recv())
        await asyncio.sleep(0.05)
        assert not ack1_task.done()  # deferred: seq 1 can't commit before 0
        assert svc.stats()["staged_bytes"] > 0
        # Stripe 0 delivers the cursor chunk: both commit, in order.
        fields, bufs = msgs[0]
        await st0.send({"request_id": "r", **fields}, blobs=bufs)
        ack0 = await asyncio.wait_for(st0.recv(), timeout=5)
        ack1 = await asyncio.wait_for(ack1_task, timeout=5)
        assert ack0["seq"] == 0 and not ack0.get("last")
        assert ack1["seq"] == 1 and ack1["last"]
        assert ack1["total"] == 4 and ack1["injected"] == 4
    finally:
        await st0.close()
        await st1.close()
    assert svc.stats()["staged_bytes"] == 0
    assert svc.stats()["streams_in_flight"] == 0
    _assert_chain_bytes(dst, hashes, payloads)


async def test_staging_budget_parks_ahead_chunks_without_deadlock():
    """An out-of-order chunk larger than the staging budget parks at
    admission instead of staging; it is re-admitted budget-free once the
    commit cursor reaches its seq. In-order chunks always pass."""
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    svc._staging_budget = 1  # no out-of-order chunk ever fits
    transport = InMemoryTransport()
    await transport.register_engine("kv", svc)
    hashes = compute_block_hashes(list(range(4 * PAGE)), PAGE, salt=0)
    src = _core(num_pages=32)
    payloads = _commit_chain(src, hashes)
    msgs = _chunk_msgs(hashes, payloads, chunk_pages=2)

    st0 = await transport.open_duplex("mem://kv", _open_req("r", stripe=0), Context())
    st1 = await transport.open_duplex("mem://kv", _open_req("r", stripe=1), Context())
    try:
        fields, bufs = msgs[1]
        await st1.send({"request_id": "r", **fields}, blobs=bufs)
        ack1_task = asyncio.create_task(st1.recv())
        await asyncio.sleep(0.05)
        assert not ack1_task.done()
        assert svc.stats()["staged_bytes"] == 0  # parked BEFORE staging
        fields, bufs = msgs[0]
        await st0.send({"request_id": "r", **fields}, blobs=bufs)
        ack0 = await asyncio.wait_for(st0.recv(), timeout=5)
        ack1 = await asyncio.wait_for(ack1_task, timeout=5)
        assert ack0["seq"] == 0 and ack1["last"]
    finally:
        await st0.close()
        await st1.close()
    _assert_chain_bytes(dst, hashes, payloads)


async def test_all_stripes_closing_mid_stream_rolls_back():
    """The sender dying (every stripe connection dropping) with the session
    incomplete triggers an immediate full rollback — pins released, session
    gone — without waiting for the abandoned-stream sweep."""
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    transport = InMemoryTransport()
    await transport.register_engine("kv", svc)
    hashes = compute_block_hashes(list(range(4 * PAGE)), PAGE, salt=0)
    src = _core(num_pages=32)
    payloads = _commit_chain(src, hashes)
    msgs = _chunk_msgs(hashes, payloads, chunk_pages=2)
    free0 = dst.allocator.num_free()

    st0 = await transport.open_duplex("mem://kv", _open_req("r", stripe=0), Context())
    st1 = await transport.open_duplex("mem://kv", _open_req("r", stripe=1), Context())
    fields, bufs = msgs[0]
    await st0.send({"request_id": "r", **fields}, blobs=bufs)
    ack0 = await asyncio.wait_for(st0.recv(), timeout=5)
    assert ack0["injected"] == 2
    assert svc.stats()["streams_in_flight"] == 1
    # Sender dies: both stripes close without the last chunk.
    await st0.close()
    await st1.close()
    assert svc.rollbacks == 1
    assert svc.stats()["streams_in_flight"] == 0
    # Committed prefix stays valid but unpinned: fully reclaimable.
    pids = dst.allocator.match_prefix(hashes[:2])
    assert len(pids) == 2
    dst.allocator.release(pids)
    dst.allocator.clear_cache()
    assert dst.allocator.num_free() == free0


async def test_new_sid_replaces_stale_session():
    """A fresh attempt (new sid) for the same request id replaces a stale
    session, rolling it back iff it had ingested anything — the v2 seq-0
    replacement rule carried over to v3."""
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    transport = InMemoryTransport()
    await transport.register_engine("kv", svc)
    hashes = compute_block_hashes(list(range(4 * PAGE)), PAGE, salt=0)
    src = _core(num_pages=32)
    payloads = _commit_chain(src, hashes)
    msgs = _chunk_msgs(hashes, payloads, chunk_pages=2)

    # Attempt 1 ingests chunk 0 then stalls (sender hung, stream not closed).
    st_old = await transport.open_duplex(
        "mem://kv", _open_req("r", sid="attempt-1", stripes=1), Context())
    fields, bufs = msgs[0]
    await st_old.send({"request_id": "r", **fields}, blobs=bufs)
    await asyncio.wait_for(st_old.recv(), timeout=5)
    assert svc.stats()["streams_in_flight"] == 1

    # Attempt 2 (new sid) replaces it: the stale session rolls back first.
    # (Attach runs when the engine generator first advances — give the
    # event loop a beat before asserting.)
    st_new = await transport.open_duplex(
        "mem://kv", _open_req("r", sid="attempt-2", stripes=1), Context())
    await asyncio.sleep(0.05)
    assert svc.rollbacks == 1
    try:
        for fields, bufs in msgs:
            await st_new.send({"request_id": "r", **fields}, blobs=bufs)
            ack = await asyncio.wait_for(st_new.recv(), timeout=5)
            assert "stream_error" not in ack
        assert ack["last"] and ack["injected"] == 4
    finally:
        await st_new.close()
        await st_old.close()
    _assert_chain_bytes(dst, hashes, payloads)


# -- blob frame codec ---------------------------------------------------------


def test_blob_codec_roundtrip_and_crc():
    rng = np.random.default_rng(0)
    shape = (CFG.num_layers, PAGE, CFG.kv_dim)
    payloads = [
        (rng.standard_normal(shape).astype(np.float32),
         rng.standard_normal(shape).astype(np.float32))
        for _ in range(3)
    ]
    hashes = [11, 22, 33]
    parents = [None, 11, 22]
    meta, bufs, nbytes = pack_chunk_blob(hashes, parents, payloads)
    assert nbytes == sum(k.nbytes + v.nbytes for k, v in payloads)
    assert sum(b.nbytes for b in bufs) == nbytes
    # The wire carries the buffers as one concatenated body.
    blocks = blob_to_blocks(meta, b"".join(bytes(b) for b in bufs))
    assert [b["hash"] for b in blocks] == hashes
    assert [b["parent"] for b in blocks] == parents
    for blk, (k, v) in zip(blocks, payloads):
        assert block_crc_ok(blk)
        k_got, v_got = unpack_payload(blk)
        np.testing.assert_array_equal(k_got, k)
        np.testing.assert_array_equal(v_got, v)
    # A flipped payload byte fails that block's crc (and only that block's).
    body = bytearray(b"".join(bytes(b) for b in bufs))
    body[0] ^= 0xFF
    tampered = blob_to_blocks(meta, bytes(body))
    assert not block_crc_ok(tampered[0])
    assert block_crc_ok(tampered[1]) and block_crc_ok(tampered[2])
    # A truncated body is a framing error, not a silent short chunk.
    with pytest.raises(ValueError, match="blob length mismatch"):
        blob_to_blocks(meta, bytes(body[:-1]))


def test_blob_codec_handles_extension_dtypes():
    """bfloat16 (no buffer-protocol format char) must round-trip: the real
    cache dtype on hardware is bf16."""
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    k = np.arange(32, dtype=np.float32).astype(bf16).reshape(2, 4, 4)
    v = (np.arange(32, dtype=np.float32) * 2).astype(bf16).reshape(2, 4, 4)
    meta, bufs, nbytes = pack_chunk_blob([7], [None], [(k, v)])
    assert nbytes == k.nbytes + v.nbytes
    assert meta[0]["dtype"] == str(bf16)
    [blk] = blob_to_blocks(meta, b"".join(bytes(b) for b in bufs))
    assert block_crc_ok(blk)
    k_got, v_got = unpack_payload(blk)
    assert k_got.dtype == bf16
    np.testing.assert_array_equal(k_got, k)
    np.testing.assert_array_equal(v_got, v)


# -- config + fallback --------------------------------------------------------


def test_wire_env_knobs(monkeypatch):
    monkeypatch.delenv("DYN_KV_CHUNK_PAGES", raising=False)
    monkeypatch.delenv("DYN_KV_WIRE_STREAMS", raising=False)
    assert default_chunk_pages() == 64
    assert default_wire_streams() == 4
    monkeypatch.setenv("DYN_KV_CHUNK_PAGES", "16")
    monkeypatch.setenv("DYN_KV_WIRE_STREAMS", "8")
    assert default_chunk_pages() == 16
    assert default_wire_streams() == 8
    monkeypatch.setenv("DYN_KV_CHUNK_PAGES", "garbage")
    monkeypatch.setenv("DYN_KV_WIRE_STREAMS", "-3")
    assert default_chunk_pages() == 64  # unparseable -> default
    assert default_wire_streams() == 0  # clamped: negatives pin v2


async def test_duplex_unsupported_falls_back_to_v2(monkeypatch):
    """A transport without a duplex plane serves the same transfer over the
    v2 single-stream protocol — silently, before any stream state exists."""
    src = _core(num_pages=32)
    hashes = compute_block_hashes(list(range(4 * PAGE)), PAGE, salt=0)
    payloads = _commit_chain(src, hashes)
    transport = InMemoryTransport()
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    await transport.register_engine("kv", svc)

    async def no_duplex(address, request, context):
        raise DuplexUnsupportedError("no duplex for test")

    monkeypatch.setattr(transport, "open_duplex", no_duplex)
    out = await send_blocks_chunked(
        transport, "mem://kv", "r", src, hashes, chunk_pages=2, streams=4)
    assert "protocol" not in out  # v2 loop served it
    assert out["injected"] == 4
    assert svc.stats()["paths"]["host_chunked"]["transfers"] == 1
    _assert_chain_bytes(dst, hashes, payloads)


@pytest.mark.e2e
async def test_striped_over_real_tcp():
    """Wire v3 over real sockets: blob frames, striped connections, byte
    exactness, and clean teardown on the TcpTransport duplex plane."""
    from dynamo_tpu.runtime.tcp import TcpTransport

    src = _core(num_pages=32)
    hashes = compute_block_hashes(list(range(6 * PAGE)), PAGE, salt=0)
    payloads = _commit_chain(src, hashes)
    dst = _core(num_pages=32)
    svc = KvTransferService(dst)
    server = TcpTransport(host="127.0.0.1")
    client = TcpTransport(host="127.0.0.1")
    try:
        await server.register_engine("kv", svc)
        addr = server.address_of("kv")
        out = await send_blocks_chunked(
            client, addr, "r", src, hashes, chunk_pages=2, streams=3)
        assert out["protocol"] == "v3" and out["streams"] == 3
        assert out["injected"] == 6
        _assert_chain_bytes(dst, hashes, payloads)
        assert svc.stats()["wire_conns"] == 0
        assert svc.stats()["paths"]["host_striped"]["transfers"] == 1
    finally:
        await client.close()
        await server.close()
