"""Pallas paged-attention decode kernel vs the XLA reference formulation.

Runs the kernel in interpret mode on CPU (bit-exact semantics, no TPU
needed); a TPU-marked variant compares on-device when a chip is present.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import paged_attention_reference
from dynamo_tpu.ops.pallas_paged import decode_supported, paged_decode_attention


def _random_case(rng, *, b, n_heads, n_kv, head_dim, page_size, pages_per_seq, max_len):
    width = n_kv * head_dim
    num_pages = b * pages_per_seq + 1
    k = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, 1, n_heads, head_dim)), jnp.float32)
    # Distinct pages per sequence (page 0 reserved as null).
    tables = jnp.asarray(
        1 + rng.permutation(num_pages - 1)[: b * pages_per_seq].reshape(b, pages_per_seq),
        jnp.int32,
    )
    positions = jnp.asarray(rng.integers(0, max_len, (b, 1)), jnp.int32)
    return q, k, v, tables, positions


@pytest.mark.parametrize(
    "b,n_heads,n_kv,head_dim,pages_per_seq",
    [
        (4, 8, 2, 64, 8),   # llama-3.2-1b-like GQA, head_dim 64
        (2, 8, 8, 16, 4),   # MHA, small head_dim (interpret only)
        (3, 4, 1, 128, 16), # MQA, head_dim 128, non-pow2 batch
    ],
)
def test_decode_kernel_matches_reference(b, n_heads, n_kv, head_dim, pages_per_seq):
    rng = np.random.default_rng(0)
    page_size = 16
    q, k, v, tables, positions = _random_case(
        rng, b=b, n_heads=n_heads, n_kv=n_kv, head_dim=head_dim,
        page_size=page_size, pages_per_seq=pages_per_seq,
        max_len=page_size * pages_per_seq,
    )
    scale = head_dim**-0.5
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k, v, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_decode_kernel_tail_block_clamps():
    """pages_per_seq > pages_per_block: the tail compute block reaches past
    the table and must clamp page indices (masked by length) — the deep-block
    path every page-16 serving config hits at long context."""
    import dynamo_tpu.ops.pallas_paged as pp

    rng = np.random.default_rng(3)
    page_size, pages_per_seq = 16, 9
    # Force small blocks so multiple blocks + a ragged tail exist.
    orig = pp._pages_per_block
    pp._pages_per_block = lambda pps, ps, *a: 4  # bk=64; 9 pages -> 3 blocks, tail ragged
    try:
        q, k, v, tables, positions = _random_case(
            rng, b=3, n_heads=8, n_kv=2, head_dim=64,
            page_size=page_size, pages_per_seq=pages_per_seq,
            max_len=page_size * pages_per_seq,
        )
        positions = jnp.asarray([[143], [64], [127]], jnp.int32)  # full, block edge, mid
        scale = 0.125
        want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
        got = paged_decode_attention(q, k, v, tables, positions, scale=scale, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)
    finally:
        pp._pages_per_block = orig


def test_decode_kernel_fp8_cache():
    """Sub-2-byte KV caches upcast to bf16 inside the kernel; results stay
    close to the f32 reference (fp8 storage error only)."""
    rng = np.random.default_rng(5)
    q, k, v, tables, positions = _random_case(
        rng, b=2, n_heads=8, n_kv=2, head_dim=64, page_size=16, pages_per_seq=4, max_len=64,
    )
    k8 = k.astype(jnp.float8_e4m3fn)
    v8 = v.astype(jnp.float8_e4m3fn)
    scale = 0.125
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k8, v8, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0.15, rtol=0.15)


def test_decode_kernel_length_one():
    """Position 0 (only the just-written token) must not read other pages."""
    rng = np.random.default_rng(1)
    q, k, v, tables, positions = _random_case(
        rng, b=2, n_heads=4, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=4, max_len=1,
    )
    positions = jnp.zeros_like(positions)
    scale = 0.125
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k, v, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_decode_supported_on_engine_layout():
    """The support predicate must accept the engine's flat [P, ps, W] cache."""
    q = jnp.zeros((2, 1, 32, 64))
    k = jnp.zeros((8, 16, 8 * 64))  # llama-3.2-1b: n_kv=8, hd=64 -> W=512
    assert decode_supported(q, k)
    k_bad = jnp.zeros((8, 16, 8 * 64 + 8))  # W not a head multiple
    assert not decode_supported(q, k_bad)


def test_forward_dispatches_to_kernel(monkeypatch):
    """models/llama.forward with attn_impl='pallas' must reach the kernel for
    decode shapes (guards against silent fallback to the gather formulation)."""
    import dynamo_tpu.ops.attention as attention_mod
    import dynamo_tpu.ops.pallas_paged as pp
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]  # n_kv=2, hd=16 -> W=32: not lane-aligned
    hits = []
    real = pp.paged_decode_attention

    def spy(*a, **kw):
        hits.append(1)
        return real(*a, interpret=True, **{k: v for k, v in kw.items() if k != "interpret"})

    monkeypatch.setattr(pp, "paged_decode_attention", spy)

    params = llama.init_params(cfg, 0)
    k_cache, v_cache = llama.init_kv_cache(cfg, num_pages=8, page_size=4)
    b = 2
    tokens = jnp.zeros((b, 1), jnp.int32)
    positions = jnp.ones((b, 1), jnp.int32)
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    slots = jnp.asarray([[1 * 4 + 1], [3 * 4 + 1]], jnp.int32)
    last = jnp.zeros((b,), jnp.int32)

    # W=32 is not 128-lane aligned: decode_supported is False, no kernel hit,
    # and the forward still runs via the reference path.
    logits, _, _ = llama.forward(
        params, cfg, tokens, positions, k_cache, v_cache, tables, slots, last,
        attn_impl="pallas",
    )
    assert logits.shape == (b, cfg.vocab_size)
    assert not hits

    # A lane-aligned config must hit the kernel.
    import dataclasses

    cfg2 = dataclasses.replace(cfg, num_kv_heads=2, head_dim=64, num_heads=4, dtype="float32")
    params2 = llama.init_params(cfg2, 0)
    k2, v2 = llama.init_kv_cache(cfg2, num_pages=8, page_size=4)
    llama.forward(
        params2, cfg2, tokens, positions, k2, v2, tables, slots, last,
        attn_impl="pallas",
    )
    assert hits
