"""Pallas paged-attention decode kernel vs the XLA reference formulation.

Runs the kernel in interpret mode on CPU (bit-exact semantics, no TPU
needed); a TPU-marked variant compares on-device when a chip is present.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import paged_attention_reference
from dynamo_tpu.ops.pallas_paged import decode_supported, paged_decode_attention


def _random_case(rng, *, b, n_heads, n_kv, head_dim, page_size, pages_per_seq, max_len):
    width = n_kv * head_dim
    num_pages = b * pages_per_seq + 1
    k = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, 1, n_heads, head_dim)), jnp.float32)
    # Distinct pages per sequence (page 0 reserved as null).
    tables = jnp.asarray(
        1 + rng.permutation(num_pages - 1)[: b * pages_per_seq].reshape(b, pages_per_seq),
        jnp.int32,
    )
    positions = jnp.asarray(rng.integers(0, max_len, (b, 1)), jnp.int32)
    return q, k, v, tables, positions


@pytest.mark.parametrize(
    "b,n_heads,n_kv,head_dim,pages_per_seq",
    [
        (4, 8, 2, 64, 8),   # llama-3.2-1b-like GQA, head_dim 64
        (2, 8, 8, 16, 4),   # MHA, small head_dim (interpret only)
        (3, 4, 1, 128, 16), # MQA, head_dim 128, non-pow2 batch
    ],
)
def test_decode_kernel_matches_reference(b, n_heads, n_kv, head_dim, pages_per_seq):
    rng = np.random.default_rng(0)
    page_size = 16
    q, k, v, tables, positions = _random_case(
        rng, b=b, n_heads=n_heads, n_kv=n_kv, head_dim=head_dim,
        page_size=page_size, pages_per_seq=pages_per_seq,
        max_len=page_size * pages_per_seq,
    )
    scale = head_dim**-0.5
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k, v, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_decode_kernel_tail_block_clamps():
    """pages_per_seq > pages_per_block: the tail compute block reaches past
    the table and must clamp page indices (masked by length) — the deep-block
    path every page-16 serving config hits at long context."""
    import dynamo_tpu.ops.pallas_paged as pp

    rng = np.random.default_rng(3)
    page_size, pages_per_seq = 16, 9
    # Force small blocks so multiple blocks + a ragged tail exist.
    orig = pp._pages_per_block
    pp._pages_per_block = lambda pps, ps, *a: 4  # bk=64; 9 pages -> 3 blocks, tail ragged
    try:
        q, k, v, tables, positions = _random_case(
            rng, b=3, n_heads=8, n_kv=2, head_dim=64,
            page_size=page_size, pages_per_seq=pages_per_seq,
            max_len=page_size * pages_per_seq,
        )
        positions = jnp.asarray([[143], [64], [127]], jnp.int32)  # full, block edge, mid
        scale = 0.125
        want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
        got = paged_decode_attention(q, k, v, tables, positions, scale=scale, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)
    finally:
        pp._pages_per_block = orig


def test_decode_kernel_fp8_cache():
    """Sub-2-byte KV caches upcast to bf16 inside the kernel; results stay
    close to the f32 reference (fp8 storage error only)."""
    rng = np.random.default_rng(5)
    q, k, v, tables, positions = _random_case(
        rng, b=2, n_heads=8, n_kv=2, head_dim=64, page_size=16, pages_per_seq=4, max_len=64,
    )
    k8 = k.astype(jnp.float8_e4m3fn)
    v8 = v.astype(jnp.float8_e4m3fn)
    scale = 0.125
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k8, v8, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0.15, rtol=0.15)


def test_decode_kernel_length_one():
    """Position 0 (only the just-written token) must not read other pages."""
    rng = np.random.default_rng(1)
    q, k, v, tables, positions = _random_case(
        rng, b=2, n_heads=4, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=4, max_len=1,
    )
    positions = jnp.zeros_like(positions)
    scale = 0.125
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k, v, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_decode_supported_on_engine_layout():
    """The support predicate must accept the engine's flat [P, ps, W] cache."""
    q = jnp.zeros((2, 1, 32, 64))
    k = jnp.zeros((8, 16, 8 * 64))  # llama-3.2-1b: n_kv=8, hd=64 -> W=512
    assert decode_supported(q, k)
    k_bad = jnp.zeros((8, 16, 8 * 64 + 8))  # W not a head multiple
    assert not decode_supported(q, k_bad)


def test_forward_dispatches_to_kernel(monkeypatch):
    """models/llama.forward with attn_impl='pallas' must reach the kernel for
    decode shapes (guards against silent fallback to the gather formulation)."""
    import dynamo_tpu.ops.attention as attention_mod
    import dynamo_tpu.ops.pallas_paged as pp
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]  # n_kv=2, hd=16 -> W=32: not lane-aligned
    hits = []
    real = pp.paged_decode_attention

    def spy(*a, **kw):
        hits.append(1)
        return real(*a, interpret=True, **{k: v for k, v in kw.items() if k != "interpret"})

    monkeypatch.setattr(pp, "paged_decode_attention", spy)

    params = llama.init_params(cfg, 0)
    k_cache, v_cache = llama.init_kv_cache(cfg, num_pages=8, page_size=4)
    b = 2
    tokens = jnp.zeros((b, 1), jnp.int32)
    positions = jnp.ones((b, 1), jnp.int32)
    tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    slots = jnp.asarray([[1 * 4 + 1], [3 * 4 + 1]], jnp.int32)
    last = jnp.zeros((b,), jnp.int32)

    # W=32 is not 128-lane aligned: decode_supported is False, no kernel hit,
    # and the forward still runs via the reference path.
    logits, _, _ = llama.forward(
        params, cfg, tokens, positions, k_cache, v_cache, tables, slots, last,
        attn_impl="pallas",
    )
    assert logits.shape == (b, cfg.vocab_size)
    assert not hits

    # A lane-aligned config must hit the kernel.
    import dataclasses

    cfg2 = dataclasses.replace(cfg, num_kv_heads=2, head_dim=64, num_heads=4, dtype="float32")
    params2 = llama.init_params(cfg2, 0)
    k2, v2 = llama.init_kv_cache(cfg2, num_pages=8, page_size=4)
    llama.forward(
        params2, cfg2, tokens, positions, k2, v2, tables, slots, last,
        attn_impl="pallas",
    )
    assert hits


def _pin_small_blocks(monkeypatch):
    """Force 1-page compute blocks so a handful of pages spans many blocks
    (split-K boundaries become exercisable at test sizes)."""
    import dynamo_tpu.ops.pallas_paged as pp

    monkeypatch.setattr(pp, "_pages_per_block", lambda pps, ps, *a: 1)


@pytest.mark.parametrize("num_splits", [2, 4, 8])
def test_split_k_matches_reference_ragged(monkeypatch, num_splits):
    """Split-K partials + LSE combine vs reference across ragged lengths:
    a length shorter than one split's slice, lengths that leave tail splits
    completely empty, and length <= page_size."""
    _pin_small_blocks(monkeypatch)  # bk = page_size = 16; 8 pages -> 8 blocks
    rng = np.random.default_rng(7)
    q, k, v, tables, positions = _random_case(
        rng, b=4, n_heads=8, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=8, max_len=128,
    )
    # length 11 (single block — every later split empty), 101, 128 (full),
    # 16 (== page_size exactly).
    positions = jnp.asarray([[10], [100], [127], [15]], jnp.int32)
    scale = 0.125
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(
        q, k, v, tables, positions, scale=scale, interpret=True,
        num_splits=num_splits,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_split_k_fp8_cache_through_combine(monkeypatch):
    """fp8 cache values must survive the per-split partials and the f32
    LSE combine (upcast happens inside each split's block loop)."""
    _pin_small_blocks(monkeypatch)
    rng = np.random.default_rng(11)
    q, k, v, tables, positions = _random_case(
        rng, b=2, n_heads=8, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=6, max_len=96,
    )
    positions = jnp.asarray([[95], [40]], jnp.int32)
    k8 = k.astype(jnp.float8_e4m3fn)
    v8 = v.astype(jnp.float8_e4m3fn)
    scale = 0.125
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(
        q, k8, v8, tables, positions, scale=scale, interpret=True, num_splits=3,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=0.15, rtol=0.15)


def test_split_k_single_split_matches_unsplit():
    """num_splits=1 must be bitwise identical to the auto-chosen grid at
    batch >= 8 (the combine degenerates to acc / l exactly)."""
    rng = np.random.default_rng(13)
    q, k, v, tables, positions = _random_case(
        rng, b=8, n_heads=4, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=4, max_len=64,
    )
    scale = 0.125
    a = paged_decode_attention(q, k, v, tables, positions, scale=scale,
                               interpret=True, num_splits=1)
    b_ = paged_decode_attention(q, k, v, tables, positions, scale=scale,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


def test_multi_query_verify_rows_match_reference():
    """T_q > 1 gappy rows (speculative verify layout): per-row causal mask
    vs the reference's key_pos <= positions mask, including a padding row
    whose trailing columns carry position 0."""
    rng = np.random.default_rng(17)
    b, t_q, n_heads, n_kv, head_dim = 3, 4, 8, 2, 64
    page_size, pages_per_seq = 16, 4
    width = n_kv * head_dim
    num_pages = b * pages_per_seq + 1
    k = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, t_q, n_heads, head_dim)), jnp.float32)
    tables = jnp.asarray(
        1 + rng.permutation(num_pages - 1)[: b * pages_per_seq].reshape(b, pages_per_seq),
        jnp.int32,
    )
    # Row 0: contiguous verify window; row 1: decode token + padding zeros
    # (mixed spec batch); row 2: full-width window ending at the last slot.
    positions = jnp.asarray(
        [[37, 38, 39, 40], [12, 0, 0, 0], [60, 61, 62, 63]], jnp.int32
    )
    scale = head_dim**-0.5
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_decode_attention(q, k, v, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_multi_query_bitwise_matches_per_position_decode(monkeypatch):
    """Losslessness invariant: a T_q = K+1 verify row must score token t
    EXACTLY as a T_q = 1 decode of token t would (same block partition,
    same split count -> same accumulation order; the extra masked blocks a
    longer row walks contribute exact zeros)."""
    _pin_small_blocks(monkeypatch)
    rng = np.random.default_rng(19)
    b, t_q = 2, 3
    q, k, v, tables, _ = _random_case(
        rng, b=b, n_heads=8, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=6, max_len=96,
    )
    q = jnp.asarray(rng.standard_normal((b, t_q, 8, 64)), jnp.float32)
    positions = jnp.asarray([[50, 51, 52], [7, 8, 9]], jnp.int32)
    scale = 0.125
    multi = paged_decode_attention(
        q, k, v, tables, positions, scale=scale, interpret=True, num_splits=2,
    )
    for t in range(t_q):
        single = paged_decode_attention(
            q[:, t : t + 1], k, v, tables, positions[:, t : t + 1],
            scale=scale, interpret=True, num_splits=2,
        )
        np.testing.assert_array_equal(
            np.asarray(multi[:, t : t + 1]), np.asarray(single)
        )


def test_verify_dispatch_reaches_kernel_no_fallback(monkeypatch):
    """paged_attention_pallas with contiguous_positions=False and a
    supported shape must use the multi-query kernel and record no
    fallback (the spec-verify fast path)."""
    import dynamo_tpu.ops.pallas_paged as pp

    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(23)
    b, t_q = 2, 3
    q, k, v, tables, _ = _random_case(
        rng, b=b, n_heads=8, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=4, max_len=64,
    )
    q = jnp.asarray(rng.standard_normal((b, t_q, 8, 64)), jnp.float32)
    positions = jnp.asarray([[20, 22, 23], [5, 6, 8]], jnp.int32)  # gappy
    before = pp.fallback_snapshot()
    got = pp.paged_attention_pallas(
        q, k, v, tables, positions, scale=0.125, contiguous_positions=False,
    )
    after = pp.fallback_snapshot()
    assert not [s for s in after if s.startswith("verify") and after[s] != before.get(s, 0)]
    want = paged_attention_reference(q, k, v, tables, positions, scale=0.125)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_verify_fallback_recorded_for_unsupported_t(monkeypatch):
    """A verify batch wider than the VMEM row cap must fall back and be
    counted under the distinct 'verify' phase (not 'prefill')."""
    import dynamo_tpu.ops.pallas_paged as pp

    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("DYN_VERIFY_T_MAX", "2")
    rng = np.random.default_rng(29)
    b, t_q = 1, 3
    q, k, v, tables, _ = _random_case(
        rng, b=b, n_heads=8, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=4, max_len=64,
    )
    q = jnp.asarray(rng.standard_normal((b, t_q, 8, 64)), jnp.float32)
    positions = jnp.asarray([[10, 12, 13]], jnp.int32)
    before = pp.fallback_snapshot()
    got = pp.paged_attention_pallas(
        q, k, v, tables, positions, scale=0.125, contiguous_positions=False,
    )
    after = pp.fallback_snapshot()
    verify_keys = [s for s in after if s.startswith("verify:")
                   and after[s] > before.get(s, 0)]
    assert verify_keys
    want = paged_attention_reference(q, k, v, tables, positions, scale=0.125)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_dma_ring_depth_env(monkeypatch):
    """Deeper DMA rings must not change results (slot assignment is a pure
    function of the global block index)."""
    rng = np.random.default_rng(31)
    q, k, v, tables, positions = _random_case(
        rng, b=3, n_heads=8, n_kv=2, head_dim=64, page_size=16,
        pages_per_seq=8, max_len=128,
    )
    scale = 0.125
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    for depth in ("2", "3", "6"):
        monkeypatch.setenv("DYN_DECODE_DMA_DEPTH", depth)
        # The ring depth is resolved at trace time; identical shapes would
        # otherwise reuse the previous depth's compiled program.
        paged_decode_attention.clear_cache()
        got = paged_decode_attention(
            q, k, v, tables, positions, scale=scale, interpret=True,
            num_splits=2,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
