"""Qwen2-VL image preprocessing parity vs HF Qwen2VLImageProcessor: the
smart_resize geometry, normalization, and the merge-group patch flattening
must produce bit-comparable pixel tensors (the tower's golden parity in
test_golden_qwen2vl.py feeds patches directly; this pins the path from
image bytes to those patches)."""

import io

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from dynamo_tpu.models.qwen2_vl import (  # noqa: E402
    Qwen2VLVisionConfig,
    mrope_position_ids,
    preprocess_qwen2vl,
    smart_resize,
)


def _png(size, color=(200, 30, 90)):
    from PIL import Image

    img = Image.new("RGB", size, color)
    # Non-uniform content so patch ORDER errors cannot cancel out.
    px = img.load()
    for x in range(size[0]):
        for y in range(size[1]):
            px[x, y] = ((x * 7) % 256, (y * 11) % 256, (x * y) % 256)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


def test_patches_match_hf_processor():
    from transformers.models.qwen2_vl.image_processing_qwen2_vl import Qwen2VLImageProcessor
    from PIL import Image

    cfg = Qwen2VLVisionConfig(patch_size=14)  # real geometry
    proc = Qwen2VLImageProcessor(
        min_pixels=cfg.min_pixels, max_pixels=cfg.max_pixels,
    )
    data = _png((130, 90))
    out = proc(images=[Image.open(io.BytesIO(data))], return_tensors="np")
    got_patches, got_grid = preprocess_qwen2vl(data, cfg)
    assert tuple(out["image_grid_thw"][0]) == got_grid
    want = out["pixel_values"]
    assert got_patches.shape == want.shape
    # Bicubic resampling differs slightly between PIL modes; the grid,
    # ordering, and normalization must agree tightly.
    np.testing.assert_allclose(got_patches, want, atol=0.05, rtol=0.05)
    # Exact agreement on the overwhelming majority of values.
    assert (np.abs(got_patches - want) < 1e-3).mean() > 0.95


def test_smart_resize_bounds():
    cfg = Qwen2VLVisionConfig()
    factor = cfg.patch_size * cfg.spatial_merge_size
    for h, w in [(90, 130), (2000, 1500), (30, 30), (56, 4000)]:
        hb, wb = smart_resize(h, w, factor, cfg.min_pixels, cfg.max_pixels)
        assert hb % factor == 0 and wb % factor == 0
        assert cfg.min_pixels <= hb * wb <= cfg.max_pixels
    with pytest.raises(ValueError):
        smart_resize(10, 4000, factor, cfg.min_pixels, cfg.max_pixels)


def test_mrope_ids_reject_mismatched_grids():
    with pytest.raises(ValueError, match="vision span"):
        mrope_position_ids([1, 9, 9, 2], [(1, 4, 4)], image_token_id=9)
    with pytest.raises(ValueError, match="grids"):
        mrope_position_ids([1, 9, 9, 9, 9, 2, 9, 9, 9, 9], [(1, 4, 4)], image_token_id=9)
