"""HTTP frontend E2E over the in-process runtime: real aiohttp server + client,
tiny JAX engine worker, discovery-driven model registration."""

import asyncio
import json

import aiohttp

from dynamo_tpu.launch import run_local


async def test_batch_input_mode(tmp_path, capsys):
    """`--input batch:file.jsonl`: every entry answered, output.jsonl written,
    throughput summary printed (reference dynamo-run in=batch)."""
    import json

    from dynamo_tpu.launch import run_batch_input

    src = tmp_path / "in.jsonl"
    src.write_text('{"text": "hello"}\n{"text": "world"}\n')
    handles = await run_local("test-tiny", port=0, mock=True, num_pages=64, max_batch_size=8)
    try:
        await run_batch_input(handles["port"], "test-tiny", str(src), concurrency=2)
    finally:
        await stop_stack(handles)
    out = (tmp_path / "output.jsonl").read_text().splitlines()
    assert len(out) == 2
    docs = [json.loads(line) for line in out]
    assert all(d["finish_reason"] == "length" for d in docs)
    assert all(d["tokens_out"] > 0 and d["elapsed_ms"] >= 0 for d in docs)
    assert "batch done: 2 entries" in capsys.readouterr().out


from tests.conftest import start_stack, stop_stack  # noqa: E402 — shared stack helpers


async def test_models_health_live_metrics():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(base + "/v1/models") as r:
                models = await r.json()
                assert r.status == 200
                assert models["data"][0]["id"] == "test-tiny"
            async with s.get(base + "/health") as r:
                assert (await r.json())["status"] == "healthy"
            async with s.get(base + "/live") as r:
                assert r.status == 200
            async with s.get(base + "/metrics") as r:
                assert "dynamo_frontend" in await r.text()
    finally:
        await stop_stack(handles)


async def test_chat_completion_aggregated():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "test-tiny",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 5,
                "temperature": 0,
            }
            async with s.post(base + "/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
                assert out["object"] == "chat.completion"
                assert out["choices"][0]["finish_reason"] == "length"
                assert out["usage"]["completion_tokens"] == 5
                assert out["usage"]["prompt_tokens"] > 0
                assert isinstance(out["choices"][0]["message"]["content"], str)
    finally:
        await stop_stack(handles)


async def test_chat_completion_streaming():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "test-tiny",
                "messages": [{"role": "user", "content": "count"}],
                "max_tokens": 4,
                "temperature": 0,
                "stream": True,
                "stream_options": {"include_usage": True},
            }
            async with s.post(base + "/v1/chat/completions", json=body) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                chunks, done = [], False
                async for line in r.content:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        done = True
                        break
                    chunks.append(json.loads(payload))
                assert done
                assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
                assert chunks[-1]["choices"][0]["finish_reason"] == "length"
                assert chunks[-1].get("usage", {}).get("completion_tokens") == 4
    finally:
        await stop_stack(handles)


async def test_completions_endpoint():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "prompt": "abc", "max_tokens": 3, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                out = await r.json()
                assert r.status == 200
                assert out["object"] == "text_completion"
                assert out["usage"]["completion_tokens"] == 3
    finally:
        await stop_stack(handles)


async def test_client_supplied_tenant_id_never_passes_through(monkeypatch):
    """Tenant identity rides the x-dynamo-tenant header (a gateway stamps
    it); a tenant_id in the request body is client-controlled and must be
    dropped, or clients could impersonate another tenant's quota — and the
    header must win over any body value when both are present."""
    from dynamo_tpu.preprocessor import OpenAIPreprocessor

    seen = []
    orig = OpenAIPreprocessor.preprocess

    def spy(self, body, **kw):
        req = orig(self, body, **kw)
        seen.append(req.tenant_id)
        return req

    monkeypatch.setattr(OpenAIPreprocessor, "preprocess", spy)
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "test-tiny", "prompt": "a", "max_tokens": 1,
                "temperature": 0, "tenant_id": "victim",
            }
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200
            async with s.post(
                base + "/v1/completions", json=body,
                headers={"x-dynamo-tenant": "acme"},
            ) as r:
                assert r.status == 200
        assert seen == [None, "acme"]
    finally:
        await stop_stack(handles)


async def test_error_paths():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(base + "/v1/chat/completions", json={"messages": []}) as r:
                assert r.status == 400  # no model
            async with s.post(
                base + "/v1/chat/completions",
                json={"model": "nope", "messages": [{"role": "user", "content": "x"}]},
            ) as r:
                assert r.status == 404
            async with s.post(base + "/v1/chat/completions", data=b"{bad json") as r:
                assert r.status == 400
            async with s.post(base + "/v1/completions", json={"model": "test-tiny"}) as r:
                assert r.status == 400  # missing prompt
    finally:
        await stop_stack(handles)


async def test_clear_kv_blocks_and_stop_strings():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            # 40-byte prompt -> fills at least two 16-token pages -> cacheable.
            body = {"model": "test-tiny", "prompt": "x" * 40, "max_tokens": 8, "temperature": 0}
            async with s.post(base + "/v1/completions", json=body) as r:
                assert r.status == 200
            async with s.post(base + "/clear_kv_blocks") as r:
                out = await r.json()
                assert r.status == 200 and out["cleared"] >= 1
    finally:
        await stop_stack(handles)


async def test_concurrent_requests_share_engine():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:

            async def one(prompt):
                body = {"model": "test-tiny", "prompt": prompt, "max_tokens": 6, "temperature": 0}
                async with s.post(base + "/v1/completions", json=body) as r:
                    return (await r.json())["choices"][0]["text"]

            results = await asyncio.gather(*[one(f"p{i}") for i in range(4)])
            assert len(results) == 4
            # Determinism: same prompt again gives same text.
            assert await one("p0") == results[0]
    finally:
        await stop_stack(handles)


async def test_embeddings_endpoint():
    """/v1/embeddings serves normalized vectors through the full pipeline
    (preprocess -> route -> worker encoder) for string and batch inputs."""
    import math

    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "test-tiny", "input": ["hello world", "different text"]}
            async with s.post(base + "/v1/embeddings", json=body) as r:
                assert r.status == 200, await r.text()
                out = await r.json()
            assert out["object"] == "list" and len(out["data"]) == 2
            v0 = out["data"][0]["embedding"]
            v1 = out["data"][1]["embedding"]
            assert len(v0) == len(v1) > 0
            assert abs(math.fsum(x * x for x in v0) - 1.0) < 1e-3  # L2-normalized
            assert v0 != v1
            assert out["usage"]["prompt_tokens"] > 0

            # Same input -> identical embedding (deterministic encoder).
            async with s.post(base + "/v1/embeddings", json={"model": "test-tiny", "input": "hello world"}) as r:
                again = (await r.json())["data"][0]["embedding"]
            assert again == v0

            # Error paths.
            async with s.post(base + "/v1/embeddings", json={"model": "nope", "input": "x"}) as r:
                assert r.status == 404
            async with s.post(base + "/v1/embeddings", json={"model": "test-tiny"}) as r:
                assert r.status == 400
    finally:
        await stop_stack(handles)


async def test_embeddings_rejects_bad_inputs():
    handles, base = await start_stack()
    try:
        async with aiohttp.ClientSession() as s:
            # Empty token-id input -> 400 (would otherwise produce NaN vectors).
            async with s.post(base + "/v1/embeddings", json={"model": "test-tiny", "input": [[]]}) as r:
                assert r.status == 400, await r.text()
            # Over-long input -> 400 (the encoder materializes O(T^2) attention).
            async with s.post(base + "/v1/embeddings",
                              json={"model": "test-tiny", "input": list(range(1, 90000))}) as r:
                assert r.status == 400
    finally:
        await stop_stack(handles)


def test_streaming_tool_calls_format():
    """stream=true with tools: tool-call markup is jailed and delivered as a
    tool_calls delta with finish_reason tool_calls (formatter-level check of
    the exact path _stream_response walks)."""
    from dynamo_tpu.frontend.openai_format import ChatStream
    from dynamo_tpu.frontend.tool_calls import ToolCallStreamJail
    from dynamo_tpu.protocols.common import BackendOutput, FinishReason

    jail = ToolCallStreamJail()
    fmt = ChatStream("m")
    chunks = []
    for piece, fin in [("<tool_call>", None), ('{"name":"f","arguments":{}}', None),
                       ("</tool_call>", FinishReason.STOP)]:
        safe = jail.push(piece)
        if fin is None:
            if safe:
                chunks.append(fmt.text_chunk(safe))
        else:
            trailing, calls = jail.finish()
            assert calls
            chunks.append(fmt.tool_calls_final(calls, BackendOutput(finish_reason=fin)))
    last = chunks[-1]["choices"][0]
    assert last["finish_reason"] == "tool_calls"
    assert last["delta"]["tool_calls"][0]["function"]["name"] == "f"
