"""Pallas chunked-prefill (flash) kernel vs the XLA reference formulation.

Interpret mode on CPU (bit-exact semantics); the on-device tier
(tests_tpu/test_on_device.py) compares the Mosaic-compiled kernel on a
real chip.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import paged_attention_reference, write_kv
from dynamo_tpu.ops.pallas_prefill import paged_prefill_attention, prefill_supported


def _case(rng, *, b, t, n_heads, n_kv, head_dim, page_size, pages_per_seq, starts):
    """Build a paged cache holding each row's full context (history + chunk)
    with the chunk's queries at absolute positions starts[b] + t."""
    width = n_kv * head_dim
    num_pages = b * pages_per_seq + 1
    k = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((num_pages, page_size, width)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, t, n_heads, head_dim)), jnp.float32)
    tables = jnp.asarray(
        1 + rng.permutation(num_pages - 1)[: b * pages_per_seq].reshape(b, pages_per_seq),
        jnp.int32,
    )
    positions = jnp.asarray(np.asarray(starts)[:, None] + np.arange(t)[None, :], jnp.int32)
    return q, k, v, tables, positions


@pytest.mark.parametrize(
    "b,t,n_heads,n_kv,head_dim,page_size,pages_per_seq,starts",
    [
        (2, 32, 8, 2, 64, 16, 4, [0, 0]),          # whole-prompt prefill
        (2, 32, 8, 2, 64, 16, 8, [48, 16]),        # chunked continuation (history)
        (3, 24, 4, 4, 32, 8, 8, [0, 8, 40]),       # MHA, t not a block multiple
        (1, 64, 4, 1, 128, 16, 8, [32]),           # MQA, head_dim 128
    ],
)
def test_prefill_kernel_matches_reference(b, t, n_heads, n_kv, head_dim, page_size, pages_per_seq, starts):
    rng = np.random.default_rng(0)
    q, k, v, tables, positions = _case(
        rng, b=b, t=t, n_heads=n_heads, n_kv=n_kv, head_dim=head_dim,
        page_size=page_size, pages_per_seq=pages_per_seq, starts=starts,
    )
    scale = head_dim**-0.5
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    got = paged_prefill_attention(q, k, v, tables, positions, scale=scale, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)


def test_prefill_kernel_small_blocks_multi_qblock():
    """Force multiple query blocks AND multiple KV blocks per query block so
    the causal early-exit bound, DMA double buffering, and the online-softmax
    carry across blocks are all exercised."""
    import dynamo_tpu.ops.pallas_prefill as pf

    rng = np.random.default_rng(2)
    orig_bt, orig_tq = pf._block_tokens, pf._tq_for
    pf._block_tokens = lambda ps, w: 2 * ps   # bk = 32 tokens
    pf._tq_for = lambda g, t, kv, hd: 16      # 16-token query blocks
    try:
        q, k, v, tables, positions = _case(
            rng, b=2, t=48, n_heads=8, n_kv=2, head_dim=64,
            page_size=16, pages_per_seq=8, starts=[0, 64],
        )
        scale = 0.125
        want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
        got = paged_prefill_attention(q, k, v, tables, positions, scale=scale, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)
    finally:
        pf._block_tokens, pf._tq_for = orig_bt, orig_tq


def test_prefill_kernel_padding_rows_are_safe():
    """Batch-padding rows (positions all 0, table row all zeros -> null page)
    must not poison real rows and must not produce NaN."""
    rng = np.random.default_rng(3)
    q, k, v, tables, positions = _case(
        rng, b=2, t=16, n_heads=4, n_kv=2, head_dim=64,
        page_size=16, pages_per_seq=4, starts=[0, 0],
    )
    tables = tables.at[1].set(0)
    positions = positions.at[1].set(0)
    scale = 0.125
    got = paged_prefill_attention(q, k, v, tables, positions, scale=scale, interpret=True)
    want = paged_attention_reference(q, k, v, tables, positions, scale=scale)
    np.testing.assert_allclose(np.asarray(got)[0], np.asarray(want)[0], rtol=2e-2, atol=2e-2)
    assert np.isfinite(np.asarray(got)).all()


def test_prefill_kernel_sentinel_tables_clamp():
    """Table entries past the row's used range may be sentinels (-1): the
    kernel must clamp page lookups to the row's own length, never load them."""
    rng = np.random.default_rng(4)
    q, k, v, tables, positions = _case(
        rng, b=1, t=16, n_heads=4, n_kv=2, head_dim=64,
        page_size=16, pages_per_seq=8, starts=[16],
    )
    want = paged_attention_reference(q, k, v, tables, positions, scale=0.125)
    # kv_len = 32 -> 2 pages used; poison the rest of the table row.
    tables = tables.at[0, 2:].set(-1)
    got = paged_prefill_attention(q, k, v, tables, positions, scale=0.125, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)


def test_prefill_matches_incremental_decode():
    """Prefilling a chunk must equal token-by-token decode over the same
    cache — the cross-check that positions/causality line up end to end."""
    from dynamo_tpu.ops.pallas_paged import paged_decode_attention

    rng = np.random.default_rng(5)
    b, t, n_heads, n_kv, head_dim, page_size = 1, 8, 4, 2, 64, 4
    width = n_kv * head_dim
    num_pages = 4
    tables = jnp.asarray([[1, 2]], jnp.int32)
    k_cache = jnp.zeros((num_pages, page_size, width), jnp.float32)
    v_cache = jnp.zeros((num_pages, page_size, width), jnp.float32)
    new_k = jnp.asarray(rng.standard_normal((b, t, n_kv, head_dim)), jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((b, t, n_kv, head_dim)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, t, n_heads, head_dim)), jnp.float32)
    slots = jnp.asarray([[1 * page_size + i for i in range(t)]], jnp.int32)
    k_cache, v_cache = write_kv(k_cache, v_cache, new_k, new_v, slots)
    positions = jnp.arange(t, dtype=jnp.int32)[None, :]
    scale = 0.125

    pre = paged_prefill_attention(q, k_cache, v_cache, tables, positions, scale=scale, interpret=True)
    for i in range(t):
        dec = paged_decode_attention(
            q[:, i : i + 1], k_cache, v_cache, tables, positions[:, i : i + 1],
            scale=scale, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(pre[:, i : i + 1]), np.asarray(dec), rtol=2e-2, atol=2e-2
        )


def test_mixed_batch_decode_row_among_chunks():
    """Engine mixed steps pad 1-token decode rows into a T>1 batch: the row's
    single real token sits at a large start with zero padding after it. The
    kernel must (a) compute that token exactly (start/kv_len derive from the
    row's position content, not its width) and (b) early-exit the query
    blocks past the row's work — zero blocks means the first DMA must not be
    issued and the unnormalized 0/0 output must be guarded (no NaN)."""
    import dynamo_tpu.ops.pallas_prefill as pf

    rng = np.random.default_rng(6)
    orig_bt, orig_tq = pf._block_tokens, pf._tq_for
    pf._block_tokens = lambda ps, w: 2 * ps  # bk = 16 tokens
    pf._tq_for = lambda g, t, kv, hd: 8      # q blocks 1,2 of row 0 have no work
    try:
        q, k, v, tables, positions = _case(
            rng, b=3, t=24, n_heads=4, n_kv=2, head_dim=64,
            page_size=8, pages_per_seq=16, starts=[100, 0, 40],
        )
        # Row 0 becomes a decode row: one real token at position 100, zero
        # padding after it (exactly what the engine's mixed batch builds).
        positions = positions.at[0, 1:].set(0)
        scale = 64**-0.5
        want = np.asarray(paged_attention_reference(q, k, v, tables, positions, scale=scale))
        got = np.asarray(paged_prefill_attention(q, k, v, tables, positions, scale=scale, interpret=True))
        assert np.isfinite(got).all()
        # Decode row: only its real token is consumed by the engine.
        np.testing.assert_allclose(got[0, :1], want[0, :1], rtol=2e-2, atol=2e-2)
        # Chunk rows (fresh prefill + mid-prompt continuation): exact in full.
        np.testing.assert_allclose(got[1:], want[1:], rtol=2e-2, atol=2e-2)
    finally:
        pf._block_tokens, pf._tq_for = orig_bt, orig_tq


def test_prefill_supported_predicate():
    q = jnp.zeros((2, 8, 32, 64))
    assert prefill_supported(q, jnp.zeros((8, 16, 8 * 64)))
    assert not prefill_supported(q, jnp.zeros((8, 16, 8 * 64 + 8)))


def test_gappy_positions_rejected_outside_jit(monkeypatch):
    """The T>1 Pallas route derives causality from row start/end only, so a
    concrete gappy-positions call must be rejected loudly unless the caller
    declares contiguous_positions=False (ADVICE r3)."""
    import dynamo_tpu.ops.pallas_prefill as pf
    from dynamo_tpu.ops.pallas_paged import paged_attention_pallas

    # The guard fires before kernel selection; route the post-guard prefill
    # calls to the reference formulation so this runs on CPU. The declared-
    # gappy call now reaches the multi-query decode kernel — interpret it.
    monkeypatch.setattr(pf, "prefill_supported", lambda *a: False)
    monkeypatch.setenv("DYNAMO_PALLAS_INTERPRET", "1")

    b, t, n_heads, head_dim, page_size = 1, 4, 4, 64, 4
    q = jnp.zeros((b, t, n_heads, head_dim), jnp.float32)
    k_cache = jnp.zeros((4, page_size, 2 * head_dim), jnp.float32)
    v_cache = jnp.zeros_like(k_cache)
    tables = jnp.asarray([[1, 2]], jnp.int32)
    gappy = jnp.asarray([[0, 2, 4, 6]], jnp.int32)  # speculative-verify shape
    with pytest.raises(ValueError, match="contiguous"):
        paged_attention_pallas(q, k_cache, v_cache, tables, gappy, scale=0.125)
    # Declared gappy: routed to the exact reference formulation instead.
    out = paged_attention_pallas(
        q, k_cache, v_cache, tables, gappy, scale=0.125, contiguous_positions=False
    )
    assert out.shape == q.shape
    # Contiguous rows (and all-zero padding rows) pass the check.
    ok = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    paged_attention_pallas(q, k_cache, v_cache, tables, ok, scale=0.125)
    pad = jnp.asarray([[0, 0, 0, 0]], jnp.int32)
    paged_attention_pallas(q, k_cache, v_cache, tables, pad, scale=0.125)
