"""Cross-process device-path KV pull (disagg/pull_transport.py).

The production wire is ``jax.experimental.transfer`` (PJRT transfer engine
— ICI/DCN device-to-device), which the CPU backend doesn't implement, so
these tests drive the FULL orchestration (descriptor protocol, staging,
sharded pull specs, scatter, commit, fallback negotiation) over stub
transports; ``tests/test_pull_two_process.py`` repeats it across two real
OS processes.
"""

import asyncio

import jax
import numpy as np
import pytest

from dynamo_tpu.disagg.pull_transport import set_transport
from dynamo_tpu.disagg.router import DisaggConfig
from dynamo_tpu.launch import run_local

aiohttp = pytest.importorskip("aiohttp")


class StubPullTransport:
    """In-process stand-in for the PJRT transfer engine: offers hold host
    copies (simulating the wire), pull re-places them with the *puller's*
    sharding — exactly the contract JaxPullTransport provides."""

    def __init__(self) -> None:
        self.offers: dict[int, list[np.ndarray]] = {}
        self.pulled = 0
        self._uuid = 0

    def address(self) -> str:
        return "stub-transfer:0"

    def new_uuid(self) -> int:
        self._uuid += 1
        return self._uuid

    def offer(self, uuid, arrays):
        self.offers[uuid] = [np.asarray(a) for a in arrays]

    def finish_offer(self, uuid):
        self.offers.pop(uuid, None)

    def pull(self, address, uuid, specs):
        assert address == self.address()
        out = []
        for arr, spec in zip(self.offers[uuid], specs):
            assert tuple(arr.shape) == tuple(spec.shape), (arr.shape, spec.shape)
            out.append(jax.device_put(arr, spec.sharding))
        self.pulled += 1
        return out


@pytest.fixture
def stub_transport():
    stub = StubPullTransport()
    set_transport(stub, supported=True)
    yield stub
    set_transport(None, None)


@pytest.mark.e2e
async def test_disagg_pull_path_e2e(stub_transport, monkeypatch):
    """Remote prefill with the in-process registry disabled: KV must arrive
    via the pull protocol (offer -> descriptor -> sharded pull -> scatter ->
    commit) and the output must match a pure-local run."""
    from dynamo_tpu.disagg import device_transfer

    monkeypatch.setattr(device_transfer.REGISTRY, "lookup", lambda addr: None)

    prompt = "p" * 48

    async def run_topology(**kw):
        handles = await run_local("test-tiny", port=0, num_pages=64, max_batch_size=8, **kw)
        try:
            async with aiohttp.ClientSession() as s:
                body = {"model": "test-tiny", "prompt": prompt, "max_tokens": 4, "temperature": 0}
                async with s.post(
                    f"http://127.0.0.1:{handles['port']}/v1/completions", json=body
                ) as r:
                    assert r.status == 200, await r.text()
                    out = await r.json()
            stats = [s.stats() for s in device_transfer.REGISTRY._services.values()]
            return out, stats
        finally:
            await handles["http"].stop()
            await handles["watcher"].close()
            for svc in handles["services"]:
                await svc.close()
            await handles["runtime"].close()

    out, stats = await run_topology(
        num_workers=1, num_prefill_workers=1,
        disagg=DisaggConfig(max_local_prefill_length=24, min_remote_prefill_blocks=1),
    )
    # The pull transport actually carried the pages.
    assert stub_transport.pulled >= 1
    assert out["usage"]["prompt_tokens_details"]["cached_tokens"] >= 32

    st = stats[0]
    assert st["device_path_blocks"] >= 2, st
    assert st["gbytes_per_sec"] > 0, st

    # Offered arrays were released after the response.
    assert not stub_transport.offers

    out_local, _ = await run_topology(num_workers=1)
    assert out["choices"][0]["text"] == out_local["choices"][0]["text"]


async def test_pull_unsupported_receiver_falls_back(monkeypatch):
    """A receiver without transfer-engine support answers pull_unsupported
    and the sender must take the packed-bytes path (send_pull_offer -> None)."""
    from types import SimpleNamespace

    from dynamo_tpu.disagg.transfer import KvTransferService
    from dynamo_tpu.runtime.engine import Context

    set_transport(None, supported=False)  # receiver probe says no
    try:
        svc = KvTransferService(SimpleNamespace(allocator=None, runner=None))
        items = []

        async def run():
            async for item in svc.generate(
                {"request_id": "r1", "pull": {"hashes": [1], "parents": [None], "n": 1,
                                              "address": "x", "uuid": 1,
                                              "k_shape": [1, 1, 4, 8], "v_shape": [1, 1, 4, 8],
                                              "k_dtype": "float32", "v_dtype": "float32"}},
                Context(),
            ):
                items.append(item)

        await run()
        assert items and items[0]["pull_unsupported"] and items[0]["injected"] == 0
    finally:
        set_transport(None, None)


async def test_pull_failure_releases_staged_pages(stub_transport):
    """A pull that raises must release the freshly-allocated destination
    pages (no leak) and report pull_failed so the sender falls back."""
    from types import SimpleNamespace

    from dynamo_tpu.engine.allocator import PageAllocator
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.disagg.transfer import KvTransferService

    alloc = PageAllocator(num_pages=8, page_size=4)
    free_before = alloc.num_free()

    class Runner:
        class _C:
            sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        k_cache = _C()

    def boom(*a, **kw):
        raise RuntimeError("wire down")

    stub_transport.pull = boom
    svc = KvTransferService(SimpleNamespace(allocator=alloc, runner=Runner()))
    items = []
    async for item in svc.generate(
        {"request_id": "r2", "pull": {"hashes": [11, 22], "parents": [None, 11], "n": 2,
                                      "address": stub_transport.address(), "uuid": 5,
                                      "k_shape": [1, 2, 4, 8], "v_shape": [1, 2, 4, 8],
                                      "k_dtype": "float32", "v_dtype": "float32"}},
        Context(),
    ):
        items.append(item)
    assert items[0].get("pull_failed")
    assert alloc.num_free() == free_before, "staged pages leaked"
