"""Cross-process device-path KV pull (disagg/pull_transport.py).

The production wire is ``jax.experimental.transfer`` (PJRT transfer engine
— ICI/DCN device-to-device), which the CPU backend doesn't implement, so
these tests drive the FULL two-phase orchestration (pull_query miss
negotiation, staging, sharded pull specs, scatter, commit, abort/fallback
negotiation) over stub transports. ``tests/test_pull_two_process.py`` runs
the descriptor exchange across two real OS processes over the runtime
transport; ``tests_tpu/test_on_device.py`` exercises the real
transfer-engine wire on hardware where the backend implements it.
"""

import asyncio

import jax
import numpy as np
import pytest

from dynamo_tpu.disagg.pull_transport import set_transport
from dynamo_tpu.disagg.router import DisaggConfig
from dynamo_tpu.launch import run_local

aiohttp = pytest.importorskip("aiohttp")


class StubPullTransport:
    """In-process stand-in for the PJRT transfer engine: offers hold host
    copies (simulating the wire), pull re-places them with the *puller's*
    sharding — exactly the contract JaxPullTransport provides."""

    def __init__(self) -> None:
        self.offers: dict[int, list[np.ndarray]] = {}
        self.pulled = 0
        self.offered = 0
        self.drained = 0
        self._uuid = 0

    def address(self) -> str:
        return "stub-transfer:0"

    def new_uuid(self) -> int:
        self._uuid += 1
        return self._uuid

    def offer(self, uuid, arrays):
        self.offered += 1
        self.offers[uuid] = [np.asarray(a) for a in arrays]

    def finish_offer(self, uuid, consumed=True):
        if self.offers.pop(uuid, None) is not None and not consumed:
            self.drained += 1

    def pull(self, address, uuid, specs):
        assert address == self.address()
        out = []
        for arr, spec in zip(self.offers[uuid], specs):
            assert tuple(arr.shape) == tuple(spec.shape), (arr.shape, spec.shape)
            out.append(jax.device_put(arr, spec.sharding))
        self.pulled += 1
        return out


@pytest.fixture
def stub_transport():
    stub = StubPullTransport()
    set_transport(stub, supported=True)
    yield stub
    set_transport(None, None)


@pytest.mark.e2e
async def test_disagg_pull_path_e2e(stub_transport, monkeypatch):
    """Remote prefill with the in-process registry disabled: KV must arrive
    via the pull protocol (pull_query -> miss set -> offer -> sharded pull
    -> scatter -> commit) and the output must match a pure-local run."""
    from dynamo_tpu.disagg import device_transfer

    monkeypatch.setattr(device_transfer.REGISTRY, "lookup", lambda addr: None)

    prompt = "p" * 48

    async def run_topology(**kw):
        handles = await run_local("test-tiny", port=0, num_pages=64, max_batch_size=8, **kw)
        try:
            async with aiohttp.ClientSession() as s:
                body = {"model": "test-tiny", "prompt": prompt, "max_tokens": 4, "temperature": 0}
                async with s.post(
                    f"http://127.0.0.1:{handles['port']}/v1/completions", json=body
                ) as r:
                    assert r.status == 200, await r.text()
                    out = await r.json()
            stats = [s.stats() for s in device_transfer.REGISTRY._services.values()]
            return out, stats
        finally:
            await handles["http"].stop()
            await handles["watcher"].close()
            for svc in handles["services"]:
                await svc.close()
            await handles["runtime"].close()

    out, stats = await run_topology(
        num_workers=1, num_prefill_workers=1,
        disagg=DisaggConfig(max_local_prefill_length=24, min_remote_prefill_blocks=1),
    )
    # The pull transport actually carried the pages.
    assert stub_transport.pulled >= 1
    assert out["usage"]["prompt_tokens_details"]["cached_tokens"] >= 32

    st = stats[0]
    assert st["device_path_blocks"] >= 2, st
    assert st["gbytes_per_sec"] > 0, st

    # Offered arrays were released after the response.
    assert not stub_transport.offers

    out_local, _ = await run_topology(num_workers=1)
    assert out["choices"][0]["text"] == out_local["choices"][0]["text"]


async def test_pull_unsupported_receiver_falls_back(monkeypatch):
    """A receiver without transfer-engine support answers pull_unsupported
    to the phase-1 query and the sender must take the packed-bytes path
    (send_pull_offer -> None) without gathering or offering anything."""
    from types import SimpleNamespace

    from dynamo_tpu.disagg.transfer import KvTransferService
    from dynamo_tpu.runtime.engine import Context

    set_transport(None, supported=False)  # receiver probe says no
    try:
        svc = KvTransferService(SimpleNamespace(allocator=None, runner=None))
        items = []

        async def run():
            async for item in svc.generate(
                {"request_id": "r1",
                 "pull_query": {"hashes": [1], "parents": [None]}},
                Context(),
            ):
                items.append(item)

        await run()
        assert items and items[0]["pull_unsupported"] and items[0]["injected"] == 0
    finally:
        set_transport(None, None)


def _make_service(num_pages=8, page_size=4):
    from types import SimpleNamespace

    from dynamo_tpu.disagg.transfer import KvTransferService
    from dynamo_tpu.engine.allocator import PageAllocator

    alloc = PageAllocator(num_pages=num_pages, page_size=page_size)

    class Runner:
        class _C:
            sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        k_cache = _C()

        def write_pages(self, page_ids, ks, vs):
            self.written = list(page_ids)

    runner = Runner()
    return KvTransferService(SimpleNamespace(allocator=alloc, runner=runner)), alloc, runner


async def _one(svc, request):
    from dynamo_tpu.runtime.engine import Context

    items = []
    async for item in svc.generate(request, Context()):
        items.append(item)
    return items[-1]


async def test_pull_failure_releases_staged_pages(stub_transport):
    """Phase 2 whose wire pull raises must release the pages staged by
    phase 1 (no leak) and report pull_failed so the sender falls back."""
    svc, alloc, _runner = _make_service()
    free_before = alloc.num_free()

    q = await _one(svc, {"request_id": "r2",
                         "pull_query": {"hashes": [11, 22], "parents": [None, 11]}})
    assert q["miss"] == [0, 1]
    assert alloc.num_free() == free_before - 2  # staged

    def boom(*a, **kw):
        raise RuntimeError("wire down")

    stub_transport.pull = boom
    out = await _one(svc, {"request_id": "r2",
                           "pull": {"address": stub_transport.address(), "uuid": 5,
                                    "k_shape": [1, 2, 4, 8], "v_shape": [1, 2, 4, 8],
                                    "k_dtype": "float32", "v_dtype": "float32"}})
    assert out.get("pull_failed")
    assert alloc.num_free() == free_before, "staged pages leaked"


async def test_warm_cache_chain_completes_in_phase_one(stub_transport):
    """A fully-cached chain must finish at pull_query: no gather, no offer,
    no transfer-server staging on the sender (the ADVICE r3 leak class)."""
    svc, alloc, _runner = _make_service()
    # Pre-commit the chain locally: hashes 11 -> 22.
    [p1] = alloc.allocate(1)
    alloc.commit(p1, 11, None, (1, 2, 3, 4))
    alloc.release([p1])
    [p2] = alloc.allocate(1)
    alloc.commit(p2, 22, 11, (5, 6, 7, 8))
    alloc.release([p2])

    q = await _one(svc, {"request_id": "warm",
                         "pull_query": {"hashes": [11, 22], "parents": [None, 11]}})
    assert q["miss"] == [] and q["injected"] == 2
    assert stub_transport.offered == 0 and stub_transport.pulled == 0
    assert not svc._pending_pulls


async def test_pull_abort_rolls_back_staging(stub_transport):
    """A sender that abandons a staged pull (pull_abort or a superseding
    packed-bytes stream) must not leak the receiver's staged pages."""
    svc, alloc, _runner = _make_service()
    free_before = alloc.num_free()
    await _one(svc, {"request_id": "r3",
                     "pull_query": {"hashes": [7, 8], "parents": [None, 7]}})
    assert alloc.num_free() == free_before - 2
    out = await _one(svc, {"request_id": "r3", "pull_abort": True})
    assert out["aborted"]
    assert alloc.num_free() == free_before
    assert not svc._pending_pulls


async def test_unconsumed_offer_is_drained(stub_transport, monkeypatch):
    """When phase 2 fails on the receiver, the sender must drain its
    un-pulled offer (finish_offer(consumed=False)) instead of leaving the
    staged device buffers pinned on the TransferServer."""
    from types import SimpleNamespace

    from dynamo_tpu.disagg import transfer as tr
    from dynamo_tpu.engine.allocator import PageAllocator
    from dynamo_tpu.runtime.engine import Context

    # Sender core with two committed pages.
    alloc = PageAllocator(num_pages=8, page_size=4)
    for h, parent in [(11, None), (22, 11)]:
        [pid] = alloc.allocate(1)
        alloc.commit(pid, h, parent, ())
        alloc.release([pid])

    class Runner:
        import threading
        io_lock = threading.RLock()
        k_cache = jax.numpy.zeros((1, 8, 4, 8), jax.numpy.float32)
        v_cache = jax.numpy.zeros((1, 8, 4, 8), jax.numpy.float32)

        @staticmethod
        def _gather_pages_fn(k, v, pids):
            return k[:, pids], v[:, pids]

    core = SimpleNamespace(allocator=alloc, runner=Runner())

    class FailingReceiverTransport:
        """Runtime transport stub: phase 1 reports misses, phase 2 fails."""

        async def generate(self, address, request, context):
            if request.get("pull_query") is not None:
                yield {"request_id": request["request_id"], "miss": [0, 1],
                       "hits": 0, "pull": True}
            elif request.get("pull") is not None:
                yield {"request_id": request["request_id"], "injected": 0,
                       "pull_failed": True}
            else:
                yield {"request_id": request["request_id"], "aborted": True}

    result = await tr.send_pull_offer(
        FailingReceiverTransport(), "addr", "rx", core, [11, 22]
    )
    assert result is None
    assert stub_transport.offered == 1
    assert stub_transport.drained == 1, "un-consumed offer was not drained"
    assert not stub_transport.offers
