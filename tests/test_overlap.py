"""Overlapped execution pipeline (ISSUE 10, generalized by ISSUE 11).

The contract under test: with ``overlap=True`` (DYN_OVERLAP) the engine
emits *bit-identical* token streams AND logprobs to ``overlap=False`` —
greedy and seeded, with chunked prefill interleaving, across late-detected
stops — because the depth-1 pipeline only changes WHEN tokens cross the
device->host boundary, never what was sampled: the chained step's input
tokens are the same values the host would have shipped, its rng fold
counter advances exactly as the synchronous loop's would, and a stop
detected one step late cancels the in-flight row (token discarded, pages
released) instead of emitting it.

ISSUE 11 erased the hot barriers, so the parity net now also pins the
newly chained compositions: mixed prefill+decode steps, penalized rows
(history written in-graph), ``spec_k>0`` (verify chain-out), and
budget-clamped final tokens (in-graph pos_limit mask instead of a host
drain). Also covered: barrier-reason accounting, the offload-batch async
gather routing, and the launch-side DYN_OVERLAP / DYN_OVERLAP_SPEC
resolution.
"""

import numpy as np
import pytest

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

PAGE = 4
_PARAMS = {}
_RUNNERS = {}


def params_for(preset):
    if preset not in _PARAMS:
        _PARAMS[preset] = llama.init_params(PRESETS[preset], 0)
    return _PARAMS[preset]


def make_core(preset="test-tiny", *, overlap=False, chunk=16, num_pages=96,
              max_batch=8, max_seq_len=256, eos=(), **cfg_kw):
    # One runner per preset, shared across tests and across the sync/overlap
    # runs of each parity pair: the jit caches live on the runner, so every
    # graph compiles once per preset for the whole module — and the parity
    # runs exercising the SAME compiled graphs is exactly the claim under
    # test (overlap changes when results move, not what is computed). A
    # fresh EngineCore re-owns the page pool; stale KV in recycled pages is
    # rewritten by prefill before anything attends to it.
    if preset not in _RUNNERS:
        _RUNNERS[preset] = ModelRunner(
            PRESETS[preset], params_for(preset), num_pages=num_pages,
            page_size=PAGE, max_batch_size=max_batch, prefill_bucket=16,
            attn_impl="reference",
        )
    return EngineCore(_RUNNERS[preset], EngineConfig(
        num_pages=num_pages, page_size=PAGE, max_batch_size=max_batch,
        max_seq_len=max_seq_len, chunk_prefill_tokens=chunk, overlap=overlap,
        eos_token_ids=tuple(eos), **cfg_kw,
    ))


def run_all(core, reqs, max_steps=400):
    """Drive to completion; returns ({seq_id: tokens}, {seq_id: logprobs})."""
    tokens, lps = {}, {}
    for req in reqs:
        seq = core.add_request(req)
        tokens[seq.seq_id] = []
        lps[seq.seq_id] = []
    steps = 0
    while core.has_work and steps < max_steps:
        for seq, out in core.step():
            tokens[seq.seq_id].extend(out.token_ids)
            if out.logprobs:
                lps[seq.seq_id].extend(out.logprobs)
        steps += 1
    assert not core.has_work, "engine did not drain"
    return tokens, lps


def _requests(vocab):
    """Greedy + seeded + logprobs + chunked prefill riding the same engine."""
    return [
        PreprocessedRequest(
            token_ids=[5, 7, 5, 7, 5, 7, 9, 11],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=14, ignore_eos=True),
        ),
        # Long prompt: its chunked prefill forces pipeline barriers while
        # the first request decodes — the overlap path must re-fill after.
        PreprocessedRequest(
            token_ids=[i % (vocab - 2) + 1 for i in range(26)],
            sampling=SamplingOptions(temperature=0.8, seed=42, logprobs=3),
            stop=StopConditions(max_tokens=10, ignore_eos=True),
        ),
        PreprocessedRequest(
            token_ids=[3, 3, 3, 3, 2, 1],
            sampling=SamplingOptions(temperature=0.7, seed=7),
            stop=StopConditions(max_tokens=10, ignore_eos=True),
        ),
    ]


# -- bit parity --------------------------------------------------------------


@pytest.mark.parametrize("preset", ["test-tiny", "test-tiny-mla"])
def test_overlap_is_bit_identical(preset):
    vocab = PRESETS[preset].vocab_size
    base_tok, base_lp = run_all(make_core(preset), _requests(vocab))
    core = make_core(preset, overlap=True)
    over_tok, over_lp = run_all(core, _requests(vocab))
    assert over_tok == base_tok
    assert over_lp == base_lp
    assert core.overlap_step_counts["overlapped"] > 0  # the path engaged
    assert core.allocator.stats().active_pages == 0


def test_overlap_bit_identical_with_staggered_admission():
    """A request admitted mid-decode forces a drain barrier; the re-filled
    pipeline must keep every stream bit-identical."""
    vocab = PRESETS["test-tiny"].vocab_size

    def run(overlap):
        core = make_core(overlap=overlap)
        reqs = _requests(vocab)
        tokens = {}
        for req in reqs[:2]:
            seq = core.add_request(req)
            tokens[seq.seq_id] = []
        late_added = False
        steps = 0
        while core.has_work and steps < 400:
            if steps == 6 and not late_added:
                seq = core.add_request(reqs[2])
                tokens[seq.seq_id] = []
                late_added = True
            for seq, out in core.step():
                tokens[seq.seq_id].extend(out.token_ids)
            steps += 1
        assert not core.has_work
        return tokens, core

    base, _ = run(False)
    over, core = run(True)
    assert over == base
    assert core.overlap_step_counts["overlapped"] > 0
    assert core.allocator.stats().active_pages == 0
    # Barrier-reason observability (ISSUE 11): every armed STEP record
    # names its pipeline mode; barrier steps carry the condition that
    # forced them, and the engine aggregates the same per-reason counts.
    from dynamo_tpu.observability.flight import STEP

    steps = [r for r in core.flight.snapshot(kind=STEP) if r.get("overlap_mode")]
    assert steps, "no armed STEP records"
    barriers = [r for r in steps if r["overlap_mode"] == "barrier"]
    assert all(r.get("barrier_reason") for r in barriers)
    assert all("chained_rows" in r for r in steps)
    assert sum(core.overlap_barrier_counts.values()) == len(barriers)


# -- late-stop cancellation --------------------------------------------------


_STREAM_CACHE = {}


def _greedy_stream(preset="test-tiny", n=16):
    """The model's deterministic greedy continuation of a fixed prompt."""
    if (preset, n) not in _STREAM_CACHE:
        toks, _ = run_all(make_core(preset), [PreprocessedRequest(
            token_ids=[5, 7, 5, 7, 9, 11],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=n, ignore_eos=True),
        )])
        _STREAM_CACHE[(preset, n)] = toks[0]
    return _STREAM_CACHE[(preset, n)]


def test_late_stop_cancels_inflight_row_no_leak_no_overrun():
    """A stop token detected one step behind the pipeline: the in-flight
    chained step has already computed the over-run token — it must never be
    emitted, and the rollback must release every page."""
    stream = _greedy_stream()
    # First token whose FIRST occurrence is a few steps in: the pipeline has
    # chained by then, so the stop is detected with a step in flight.
    stop_tok = next(t for i, t in enumerate(stream) if stream.index(t) == i and i >= 4)
    req = lambda: PreprocessedRequest(  # noqa: E731
        token_ids=[5, 7, 5, 7, 9, 11],
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=16, ignore_eos=True,
                            stop_token_ids=[stop_tok]),
    )
    base_tok, _ = run_all(make_core(), [req()])
    core = make_core(overlap=True)
    over_tok, _ = run_all(core, [req()])
    assert over_tok == base_tok
    assert over_tok[0][-1] == stop_tok
    expected = stream[: stream.index(stop_tok) + 1]
    assert over_tok[0] == expected  # never the over-run token
    assert core.overlap_step_counts["overlapped"] > 0
    assert core.allocator.stats().active_pages == 0  # rollback leaked nothing


def test_late_eos_stop_parity_and_page_accounting():
    """Same cancellation via the EOS path, with other sequences surviving
    the barrier: their streams must continue bit-identically after the
    stopped row's rollback (rng-fold continuity across the drain)."""
    stream = _greedy_stream()
    eos = next(t for i, t in enumerate(stream) if stream.index(t) == i and i >= 3)
    eos_at = stream.index(eos)
    reqs = lambda: [  # noqa: E731
        PreprocessedRequest(
            token_ids=[5, 7, 5, 7, 9, 11],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=20),
        ),
        PreprocessedRequest(
            token_ids=[3, 3, 3, 3, 2, 1],
            sampling=SamplingOptions(temperature=0.7, seed=7),
            stop=StopConditions(max_tokens=16, ignore_eos=True),
        ),
    ]
    base_tok, _ = run_all(make_core(eos=[eos]), reqs())
    core = make_core(overlap=True, eos=[eos])
    over_tok, _ = run_all(core, reqs())
    assert over_tok == base_tok
    assert over_tok[0][-1] == eos and len(over_tok[0]) == eos_at + 1
    assert len(over_tok[1]) == 16  # survivor ran to its own limit
    assert core.allocator.stats().active_pages == 0


# -- rng-fold discipline -----------------------------------------------------


def test_chained_dispatch_fold_counter_matches_sync(monkeypatch):
    """The chained step dispatches with ``sample_steps + 1`` — exactly the
    fold counter the synchronous loop would use after harvesting the
    in-flight token. Fold advances once per emitted token, never per
    dispatch."""
    core = make_core(overlap=True, chunk=0)
    calls = []
    orig = core.runner.step_async

    def spy(batch, lp_k=0, *, chain=False, chain_src=None):
        calls.append((bool(chain), int(np.asarray(batch.sample_steps)[0])))
        return orig(batch, lp_k=lp_k, chain=chain, chain_src=chain_src)

    monkeypatch.setattr(core.runner, "step_async", spy)
    seq = core.add_request(PreprocessedRequest(
        token_ids=[1, 2, 3, 4],
        sampling=SamplingOptions(temperature=0.9, seed=11),
        stop=StopConditions(max_tokens=12, ignore_eos=True),
    ))
    emitted = 0
    steps = 0
    while core.has_work and steps < 100:
        before = len(calls)
        outs = core.step()
        for chained, fold in calls[before:]:
            # Non-chained dispatch samples token number `emitted`; a chained
            # one samples token `emitted + 1` (the in-flight token between
            # them is harvested only afterwards).
            assert fold == emitted + (1 if chained else 0)
        emitted += sum(len(o.token_ids) for _, o in outs)
        steps += 1
    assert emitted == 12
    assert seq.num_generated == 12
    assert any(chained for chained, _ in calls)  # the pipeline actually chained


# -- newly chained compositions (ISSUE 11) -----------------------------------


@pytest.mark.parametrize("preset", ["test-tiny", "test-tiny-mla"])
def test_mixed_prefill_decode_interleave_chains(preset):
    """A long prompt admitted mid-decode: its chunked prefill rides the same
    overlapped steps as the decoding rows (per-row token sourcing), with
    every stream bit-identical and no 'prefill' barriers taken."""
    vocab = PRESETS[preset].vocab_size

    def run(overlap):
        core = make_core(preset, overlap=overlap, chunk=8)
        reqs = _requests(vocab)
        tokens, lps = {}, {}
        for req in reqs[:1] + reqs[2:]:
            seq = core.add_request(req)
            tokens[seq.seq_id] = []
            lps[seq.seq_id] = []
        steps = 0
        late_added = False
        while core.has_work and steps < 400:
            if steps == 3 and not late_added:
                seq = core.add_request(reqs[1])  # 26-token prompt: 4 chunks
                tokens[seq.seq_id] = []
                lps[seq.seq_id] = []
                late_added = True
            for seq, out in core.step():
                tokens[seq.seq_id].extend(out.token_ids)
                if out.logprobs:
                    lps[seq.seq_id].extend(out.logprobs)
            steps += 1
        assert not core.has_work
        return tokens, lps, core

    base_tok, base_lp, _ = run(False)
    over_tok, over_lp, core = run(True)
    assert over_tok == base_tok
    assert over_lp == base_lp
    counts = core.overlap_step_counts
    assert counts["overlapped"] > counts.get("barrier", 0)
    assert "prefill" not in core.overlap_barrier_counts  # chunks chained
    assert core.allocator.stats().active_pages == 0


def test_spec_k_chains_with_overlap():
    """overlap + spec_k compose: the verify's accepted tokens stay device
    resident and feed the next dispatch — bit-identical to the plain
    baseline with both speculation and chaining engaged."""
    reqs = lambda: [PreprocessedRequest(  # noqa: E731 - periodic prompt drafts well
        token_ids=[5, 7, 5, 7, 5, 7, 9, 11],
        sampling=SamplingOptions(temperature=0.0, logprobs=2),
        stop=StopConditions(max_tokens=12, ignore_eos=True),
    )]
    base_tok, base_lp = run_all(make_core(), reqs())
    core = make_core(overlap=True, spec_k=3)
    spec_tok, spec_lp = run_all(core, reqs())
    assert spec_tok == base_tok
    assert spec_lp == base_lp
    assert core.spec_tokens_proposed > 0  # speculation engaged
    assert core.overlap_step_counts["overlapped"] > 0  # and still pipelined


def test_overlap_spec_off_barriers_to_sync_verify():
    """DYN_OVERLAP_SPEC=0: speculation must not be silently dropped — the
    engine barriers to the synchronous verify path (reason 'spec') and
    stays bit-identical."""
    reqs = lambda: [PreprocessedRequest(  # noqa: E731
        token_ids=[5, 7, 5, 7, 5, 7, 9, 11],
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=12, ignore_eos=True),
    )]
    base_tok, _ = run_all(make_core(), reqs())
    core = make_core(overlap=True, spec_k=3, overlap_spec=False)
    spec_tok, _ = run_all(core, reqs())
    assert spec_tok == base_tok
    assert core.spec_tokens_proposed > 0  # speculation still engaged
    assert core.overlap_step_counts["overlapped"] == 0  # overlap stood down
    assert core.overlap_barrier_counts.get("spec", 0) > 0


def test_penalized_sampling_chains():
    """Penalized rows no longer barrier: the chained token's history count
    is written in-graph, so presence/frequency/repetition penalties see
    the same history the synchronous loop would."""
    req = lambda: PreprocessedRequest(  # noqa: E731
        token_ids=[5, 7, 5, 7, 9, 11],
        sampling=SamplingOptions(
            temperature=0.8, seed=3, frequency_penalty=0.5,
            presence_penalty=0.3, logprobs=2,
        ),
        stop=StopConditions(max_tokens=12, ignore_eos=True),
    )
    base_tok, base_lp = run_all(make_core(), [req()])
    core = make_core(overlap=True)
    over_tok, over_lp = run_all(core, [req()])
    assert over_tok == base_tok
    assert over_lp == base_lp
    assert core.overlap_step_counts["overlapped"] > 0  # penalties chained


@pytest.mark.parametrize("preset", ["test-tiny", "test-tiny-mla"])
def test_budget_clamped_final_token_chains(preset):
    """Rows one token from max_tokens used to force a drain (the chained
    write could overrun the page/pos budget); the in-graph pos_limit mask
    clamps it instead. A short row finishing mid-pipeline must not barrier
    the surviving rows or corrupt their streams."""
    vocab = PRESETS[preset].vocab_size
    reqs = lambda: [  # noqa: E731
        PreprocessedRequest(
            token_ids=[5, 7, 5, 7, 9, 11],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=3, ignore_eos=True),  # ends in-pipe
        ),
        PreprocessedRequest(
            token_ids=[i % (vocab - 2) + 1 for i in range(9)],
            sampling=SamplingOptions(temperature=0.7, seed=13, logprobs=2),
            stop=StopConditions(max_tokens=14, ignore_eos=True),
        ),
    ]
    base_tok, base_lp = run_all(make_core(preset), reqs())
    core = make_core(preset, overlap=True)
    over_tok, over_lp = run_all(core, reqs())
    assert over_tok == base_tok
    assert over_lp == base_lp
    assert [len(t) for t in over_tok.values()] == [3, 14]  # exact budgets
    assert core.overlap_step_counts["overlapped"] > 0
    assert core.allocator.stats().active_pages == 0


def test_multistep_rides_the_chained_pipeline_under_overlap():
    """overlap + decode_steps>1: the burst is served as K chained
    sub-dispatches inside the unified pipeline (no 'multistep' barrier
    exists anymore) — bit-identically vs the sync fused burst, admission
    drains included, and with the sub-steps counted as chained rows."""
    reqs = lambda: [  # noqa: E731
        PreprocessedRequest(
            token_ids=[5, 7, 5, 7, 9, 11],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=12, ignore_eos=True),
        ),
        PreprocessedRequest(
            token_ids=[3, 3, 3, 3, 2, 1],
            sampling=SamplingOptions(temperature=0.7, seed=7),
            stop=StopConditions(max_tokens=11, ignore_eos=True),
        ),
    ]
    base_tok, _ = run_all(make_core(decode_steps=4), reqs())
    core = make_core(overlap=True, decode_steps=4)
    over_tok = {}
    for req in reqs():
        over_tok[core.add_request(req).seq_id] = []
    max_chained = 0
    for _ in range(400):
        if not core.has_work:
            break
        for seq, out in core.step():
            over_tok[seq.seq_id].extend(out.token_ids)
        max_chained = max(max_chained, core.last_step_info.get("chained_rows", 0))
    assert not core.has_work
    assert over_tok == base_tok
    assert core.overlap_step_counts["overlapped"] > 0
    assert "multistep" not in core.overlap_barrier_counts
    # A burst step reports its sub-dispatches as chained rows: with 2 rows
    # and decode_steps=4 some step must chain more rows than the batch has.
    assert max_chained > 2
    assert core.allocator.stats().active_pages == 0


def test_multistep_chained_burst_deep_parity():
    """decode_steps sweep: the chained burst path must replay the sync
    fused burst token-for-token at several depths, including depths that
    overshoot the rows' budgets (the clamp keeps every sub-step real)."""
    vocab = PRESETS["test-tiny"].vocab_size
    reqs = lambda: [  # noqa: E731
        PreprocessedRequest(
            token_ids=[i % (vocab - 2) + 1 for i in range(7)],
            sampling=SamplingOptions(temperature=0.8, seed=3),
            stop=StopConditions(max_tokens=17, ignore_eos=True),
        ),
        PreprocessedRequest(
            token_ids=[2, 4, 6],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=5, ignore_eos=True),  # clamps k
        ),
        PreprocessedRequest(
            token_ids=[9, 9, 1, 1],
            sampling=SamplingOptions(temperature=0.5, seed=11),
            stop=StopConditions(max_tokens=13, ignore_eos=True),
        ),
    ]
    base_tok, _ = run_all(make_core(), reqs())
    for k in (2, 8):
        over_tok, _ = run_all(make_core(overlap=True, decode_steps=k), reqs())
        assert over_tok == base_tok, f"decode_steps={k} diverged"


# -- chained constrained (JSON-mode) decode ----------------------------------


def _json_core(*, overlap, chunk=16, **cfg_kw):
    from dynamo_tpu.tokenizer import ByteTokenizer

    core = make_core(overlap=overlap, chunk=chunk, **cfg_kw)
    core.set_constraint_tokenizer(ByteTokenizer())
    return core


def _json_reqs(max_tokens=24):
    from dynamo_tpu.tokenizer import ByteTokenizer

    prompt = ByteTokenizer().encode("data: ", add_bos=False)
    return [
        PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.8, seed=1, json_mode=True),
            stop=StopConditions(max_tokens=max_tokens),
        ),
        # Plain greedy row sharing every batch with the constrained rows.
        PreprocessedRequest(
            token_ids=[5, 7, 9, 11],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=10, ignore_eos=True),
        ),
        PreprocessedRequest(
            token_ids=prompt + prompt,
            sampling=SamplingOptions(
                temperature=0.7, seed=9, json_mode=True, logprobs=2
            ),
            stop=StopConditions(max_tokens=max_tokens),
        ),
    ]


@pytest.mark.parametrize("chunk", [16, 0])
def test_constrained_chained_decode_bit_identical(chunk):
    """JSON-mode rows ride the chained pipeline (lookahead mask groups
    resolve in-graph against the chained token) bit-identically — tokens
    AND logprobs — vs the sync masked loop, chunked and legacy prefill."""
    base_tok, base_lp = run_all(_json_core(overlap=False, chunk=chunk), _json_reqs())
    core = _json_core(overlap=True, chunk=chunk)
    over_tok, over_lp = run_all(core, _json_reqs())
    assert over_tok == base_tok
    assert over_lp == base_lp
    assert core.overlap_step_counts["overlapped"] > 0
    # With the lookahead enabled "constraint" never fires; residual cold
    # summaries surface as (self-curing) constraint_miss barriers instead.
    assert "constraint" not in core.overlap_barrier_counts
    assert core.constraint_mask_cache_hits > 0
    assert core.allocator.stats().active_pages == 0


def test_constrained_chained_forced_close_near_budget():
    """Tight max_tokens: budget_to_close force-closing must kick in at the
    same steps under overlap (the plan's successor masks are built at the
    row's post-emit remaining), keeping streams identical to the end."""
    for mt in (6, 9, 12):
        base_tok, base_lp = run_all(_json_core(overlap=False), _json_reqs(mt))
        core = _json_core(overlap=True)
        over_tok, over_lp = run_all(core, _json_reqs(mt))
        assert over_tok == base_tok, f"max_tokens={mt} diverged"
        assert over_lp == base_lp, f"max_tokens={mt} logprobs diverged"


def test_constraint_lookahead_disabled_barriers_every_step():
    """DYN_CONSTRAINT_LOOKAHEAD_TOKENS=0: constrained rows barrier with
    reason 'constraint' (the bench baseline) — still bit-identical."""
    base_tok, base_lp = run_all(_json_core(overlap=False), _json_reqs())
    core = _json_core(overlap=True, constraint_lookahead_tokens=0)
    over_tok, over_lp = run_all(core, _json_reqs())
    assert over_tok == base_tok
    assert over_lp == base_lp
    assert core.overlap_barrier_counts.get("constraint", 0) > 0
    assert "constraint_miss" not in core.overlap_barrier_counts


def test_overlap_off_never_touches_async_path(monkeypatch):
    """DYN_OVERLAP=0 must be bit-identical to today's loop structurally:
    step_async is never called."""
    core = make_core(overlap=False)

    def boom(*a, **k):
        raise AssertionError("step_async called with overlap off")

    monkeypatch.setattr(core.runner, "step_async", boom)
    toks, _ = run_all(core, [PreprocessedRequest(
        token_ids=[5, 7, 5, 7, 9, 11],
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=6, ignore_eos=True),
    )])
    assert len(toks[next(iter(toks))]) == 6


# -- mock runner parity (the bench probe's engine) ---------------------------


def test_mock_runner_overlap_parity():
    from dynamo_tpu.mocker import MockRunner

    def run(overlap):
        runner = MockRunner(num_pages=128, page_size=16, realtime=False, d2h_us=500.0)
        core = EngineCore(runner, EngineConfig(
            num_pages=128, page_size=16, max_batch_size=8, max_seq_len=512,
            chunk_prefill_tokens=64, overlap=overlap, enable_prefix_caching=False,
        ))
        reqs = [
            PreprocessedRequest(
                token_ids=list(range(1, 33)),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=24, ignore_eos=True),
            )
            for _ in range(3)
        ]
        tokens, _ = run_all(core, reqs)
        return tokens, core

    base, _ = run(False)
    over, core = run(True)
    assert over == base
    assert core.overlap_step_counts["overlapped"] > 0
    assert core.allocator.stats().active_pages == 0


# -- offload batching (satellite) --------------------------------------------


def test_offload_batch_prefers_async_gather():
    """KvBlockManager.offload_batch routes through read_pages_async when
    provided: one dispatched gather per batch, waited only at the tier puts."""
    from dynamo_tpu.blocks.manager import BlockManagerConfig, KvBlockManager

    reads = {"async_batches": [], "sync_batches": [], "per_page": 0}

    class Handle:
        def __init__(self, pages):
            self._pages = pages

        def wait(self):
            return [(np.zeros((1, 4, 8), np.float32),) * 2 for _ in self._pages]

    def read_pages_async(pages):
        reads["async_batches"].append(list(pages))
        return Handle(pages)

    def read_pages(pages):
        reads["sync_batches"].append(list(pages))
        return Handle(pages).wait()

    def read_page(pid):
        reads["per_page"] += 1
        return np.zeros((1, 4, 8), np.float32), np.zeros((1, 4, 8), np.float32)

    mgr = KvBlockManager(
        BlockManagerConfig(g2_capacity_blocks=16, null_storage=True),
        read_page=read_page, write_page=lambda *a: None,
    )
    mgr.offload_batch(
        [(100, 1), (101, 2), (102, 3), (100, 1)],  # one dup
        read_pages=read_pages, read_pages_async=read_pages_async,
    )
    assert reads["async_batches"] == [[1, 2, 3]]  # one batched gather, deduped
    assert reads["sync_batches"] == [] and reads["per_page"] == 0
    assert mgr.offloaded == 3


def test_core_flush_offloads_uses_runner_async_gather(monkeypatch):
    """The engine's flush routes deferred offloads through the runner's
    batched async gather — one dispatch per flush, not one per page."""
    core = make_core()
    calls = []
    orig = core.runner.read_pages_async

    def spy(pages):
        calls.append(list(pages))
        return orig(pages)

    monkeypatch.setattr(core.runner, "read_pages_async", spy)
    from dynamo_tpu.blocks.manager import BlockManagerConfig, KvBlockManager

    core.block_manager = KvBlockManager(
        BlockManagerConfig(g2_capacity_blocks=64, null_storage=True),
        read_page=core.runner.read_page, write_page=core.runner.write_page,
    )
    run_all(core, [PreprocessedRequest(
        token_ids=list(range(1, 18)),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=8, ignore_eos=True),
    )])
    assert calls, "flush_offloads never used the async gather"
    assert core.block_manager.offloaded == sum(len(c) for c in calls)


# -- launch / config resolution ----------------------------------------------


def test_launch_resolves_dyn_overlap(monkeypatch):
    from dynamo_tpu.launch import WorkerSpec
    from dynamo_tpu.model_card import ModelDeploymentCard

    card = ModelDeploymentCard(
        name="test-tiny", context_length=256, kv_page_size=PAGE, eos_token_ids=[2],
    )
    monkeypatch.delenv("DYN_OVERLAP", raising=False)
    monkeypatch.delenv("DYN_WORKER_OVERLAP", raising=False)
    monkeypatch.delenv("DYN_OVERLAP_SPEC", raising=False)
    monkeypatch.delenv("DYN_WORKER_OVERLAP_SPEC", raising=False)
    assert WorkerSpec._engine_cfg(card, {}).overlap is False
    assert WorkerSpec._engine_cfg(card, {}).overlap_spec is True  # default on
    monkeypatch.setenv("DYN_OVERLAP", "1")
    assert WorkerSpec._engine_cfg(card, {}).overlap is True
    monkeypatch.delenv("DYN_OVERLAP")
    monkeypatch.setenv("DYN_WORKER_OVERLAP", "true")
    assert WorkerSpec._engine_cfg(card, {}).overlap is True
    monkeypatch.setenv("DYN_OVERLAP_SPEC", "0")
    assert WorkerSpec._engine_cfg(card, {}).overlap_spec is False


def test_launch_resolves_constraint_lookahead(monkeypatch):
    from dynamo_tpu.launch import WorkerSpec
    from dynamo_tpu.model_card import ModelDeploymentCard

    card = ModelDeploymentCard(
        name="test-tiny", context_length=256, kv_page_size=PAGE, eos_token_ids=[2],
    )
    monkeypatch.delenv("DYN_CONSTRAINT_LOOKAHEAD_TOKENS", raising=False)
    assert WorkerSpec._engine_cfg(card, {}).constraint_lookahead_tokens == 32
    monkeypatch.setenv("DYN_CONSTRAINT_LOOKAHEAD_TOKENS", "0")
    assert WorkerSpec._engine_cfg(card, {}).constraint_lookahead_tokens == 0
    monkeypatch.setenv("DYN_CONSTRAINT_LOOKAHEAD_TOKENS", "64")
    assert WorkerSpec._engine_cfg(card, {}).constraint_lookahead_tokens == 64


def test_worker_settings_overlap_field(monkeypatch):
    from dynamo_tpu.config import load_worker_settings

    assert load_worker_settings(env={}).overlap is False
    assert load_worker_settings(env={"DYN_WORKER_OVERLAP": "1"}).overlap is True
    assert load_worker_settings(env={}).overlap_spec is True
    assert load_worker_settings(
        env={"DYN_WORKER_OVERLAP_SPEC": "0"}
    ).overlap_spec is False
