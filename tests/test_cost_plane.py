"""Device-cost plane (ISSUE 19): roofline ledger + on-demand profiler capture.

Covers the CostRegistry's two sourcing paths (background XLA extraction on
CPU, model-derived estimate fallback), the multi-step iteration scaling,
roofline math and bound classification, the metrics Counter monotonicity,
the worker/frontend HTTP surfaces (including the profiler-unavailable and
single-flight refusals), the control-tower panel, the engine-core flight
join on the mock runner, and the DYN_COST_PLANE=0 acceptance: bit-identical
tokens with zero extraction work (spied via the module global EXTRACTIONS).
"""

import os

import aiohttp
import numpy as np
import pytest

from dynamo_tpu.observability import cost as cost_mod
from dynamo_tpu.observability.cost import (
    CostRegistry,
    chip_peaks,
    cost_plane_enabled,
    decode_step_estimate,
    make_lower_thunk,
    weight_stream_bytes,
)
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context


@pytest.fixture(autouse=True)
def _cost_plane_on(monkeypatch):
    """conftest defaults DYN_COST_PLANE=0 so background extraction stays out
    of the rest of the suite; these tests exercise the plane itself, so flip
    it back on (individual tests re-override where they test the off path)."""
    monkeypatch.setenv("DYN_COST_PLANE", "1")


def _greedy_req(prompt, max_tokens=4, ignore_eos=True):
    return PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=ignore_eos),
    )


# -- peaks --------------------------------------------------------------------


def test_chip_peaks_env_override(monkeypatch):
    monkeypatch.setenv("DYN_PEAK_HBM_GBPS", "819")
    monkeypatch.setenv("DYN_PEAK_TFLOPS", "197")
    hbm, tflops, source = chip_peaks()
    assert (hbm, tflops, source) == (819.0, 197.0, "env")


def test_chip_peaks_cpu_fallback(monkeypatch):
    monkeypatch.delenv("DYN_PEAK_HBM_GBPS", raising=False)
    monkeypatch.delenv("DYN_PEAK_TFLOPS", raising=False)
    hbm, tflops, source = chip_peaks()
    # The test mesh is virtual CPU devices: documented DDR-class proxies.
    assert (hbm, tflops) == cost_mod.CPU_FALLBACK_PEAKS
    assert source.startswith("fallback:")


# -- extraction vs estimate ---------------------------------------------------


def test_xla_extraction_agrees_with_model_within_15pct():
    """The CPU-proxy acceptance: a weight-dominated f32 program (the 1B
    decode regime, where the weight stream IS the byte budget) must show
    XLA cost-analysis bytes within 15% of the modeled operand bytes."""
    import jax
    import jax.numpy as jnp

    W = jnp.zeros((2048, 2048), jnp.float32)
    x = jnp.zeros((8, 2048), jnp.float32)
    fn = jax.jit(lambda w, v: v @ w)
    modeled = float(W.nbytes + x.nbytes + 8 * 2048 * 4)

    reg = CostRegistry(peaks=(50.0, 0.5))
    before = cost_mod.EXTRACTIONS
    # Deliberately-off estimate: extraction must retroactively correct it.
    reg.submit("decode_proxy", (8,), "decode",
               lower=make_lower_thunk(fn, (W, x), {}),
               estimate={"bytes": modeled / 3, "flops": 1.0})
    reg.observe("decode_proxy", (8,), 0.010, "decode")
    assert reg.drain(timeout=60.0), "background extraction did not finish"
    assert cost_mod.EXTRACTIONS == before + 1

    rec = reg.record_for("decode_proxy")
    assert rec.source == "xla"
    assert abs(rec.bytes - modeled) / modeled < 0.15, (rec.bytes, modeled)
    led = reg.ledger()["decode"]
    # The ledger cell the estimate already touched was retro-adjusted too.
    assert abs(led["bytes_per_step"] - rec.bytes) < 1.0
    reg.close()


def test_estimate_stands_when_no_lowering_offered():
    reg = CostRegistry(peaks=(50.0, 0.5))
    reg.submit("mock", (1,), "prefill", estimate={"bytes": 1e6, "flops": 2e6})
    reg.observe("mock", (1,), 0.001, "prefill")
    rec = reg.record_for("mock")
    assert rec.source == "estimate" and rec.bytes == 1e6
    assert reg.ledger()["prefill"]["bytes"] == 1e6
    assert reg.extract_calls == 0


def test_extraction_failure_degrades_to_estimate():
    reg = CostRegistry(peaks=(50.0, 0.5))

    def bad_lower():
        raise RuntimeError("lowering exploded")

    reg.submit("bad", (2,), "decode", lower=bad_lower,
               estimate={"bytes": 7.0, "flops": 3.0})
    assert reg.drain(timeout=30.0)
    assert reg.extract_failures == 1
    rec = reg.record_for("bad")
    assert rec.source == "estimate" and rec.bytes == 7.0
    reg.close()


def test_estimate_helpers_shapes():
    """The shared helpers bench.py / profile_1b_decode consume."""
    import jax.numpy as jnp

    params = {"layer": {"w": jnp.zeros((4, 4), jnp.float32)}}

    class Cfg:
        tie_embeddings = True

        def kv_bytes_per_token(self, itemsize=2):
            return 8 * itemsize

    est = decode_step_estimate(params, Cfg(), batch=2, context_tokens=16)
    assert est["bytes"] == weight_stream_bytes(params, Cfg()) + 2 * 16 * 16
    assert est["flops"] == 2.0 * 16 * 2


# -- roofline math ------------------------------------------------------------


def test_roofline_classification():
    reg = CostRegistry(peaks=(100.0, 1.0))  # 100 GB/s, 1 TFLOP/s
    # 50 GB in 1 s -> 0.5 of the memory peak; 0.1 TFLOP -> 0.1 of compute.
    frac, bound = reg.roofline_of(50e9, 0.1e12, 1.0)
    assert bound == "memory" and frac == pytest.approx(0.5)
    frac, bound = reg.roofline_of(1e9, 0.9e12, 1.0)
    assert bound == "compute" and frac == pytest.approx(0.9)
    assert reg.roofline_of(0.0, 0.0, 1.0) == (0.0, "")
    assert reg.roofline_of(1e9, 0.0, 0.0) == (0.0, "")


def test_multi_step_scales_by_iteration_units():
    """XLA counts a fused-loop body once; observe(steps=N) must scale the
    ledger so burst dispatches account N iterations, wall unscaled."""
    reg = CostRegistry(peaks=(100.0, 1.0))
    reg.submit("multi_step", (8,), "decode", estimate={"bytes": 10.0, "flops": 4.0})
    reg.observe("multi_step", (8,), 0.002, "decode", steps=4)
    reg.observe("multi_step", (8,), 0.002, "decode", steps=4)
    led = reg.ledger()["decode"]
    assert led["bytes"] == 80.0 and led["flops"] == 32.0
    assert led["dispatches"] == 2 and led["steps"] == 8
    assert led["bytes_per_step"] == 10.0 and led["bytes_per_dispatch"] == 40.0
    rec = reg.record_for("multi_step")
    assert rec.dispatches == 2 and rec.step_units == 8
    # take_step: the engine-core join sees burst-scaled bytes once.
    assert reg.take_step() == (80.0, 32.0)
    assert reg.take_step() == (0.0, 0.0)


def test_timed_dispatch_forwards_cost_and_steps():
    from dynamo_tpu.observability.compile import timed_dispatch

    reg = CostRegistry(peaks=(100.0, 1.0))
    reg.submit("step", (1,), "decode", estimate={"bytes": 5.0, "flops": 1.0})
    with timed_dispatch(None, "step", (1,), cost=reg, kind="decode", steps=3):
        pass
    led = reg.ledger()["decode"]
    assert led["bytes"] == 15.0 and led["steps"] == 3
    # An exception inside the body suppresses the observation (no wall).
    with pytest.raises(ValueError):
        with timed_dispatch(None, "step", (1,), cost=reg, kind="decode"):
            raise ValueError("boom")
    assert reg.ledger()["decode"]["dispatches"] == 1


# -- engine-core join + metrics (mock runner) ---------------------------------


def _run_mock_core(steps=64):
    from dynamo_tpu.mocker import build_mock_core

    core = build_mock_core(realtime=False)
    core.add_request(_greedy_req([1, 2, 3, 4, 5], max_tokens=4))
    core.add_request(_greedy_req([7, 8, 9], max_tokens=4))
    for _ in range(steps):
        if not core.has_work:
            break
        core.step()
    return core


def test_step_flight_records_carry_cost_fields():
    from dynamo_tpu.observability.flight import STEP

    core = _run_mock_core()
    assert core.runner.cost_registry is not None
    records = core.flight.snapshot(kind=STEP)
    assert records
    for r in records:
        assert "hbm_bytes" in r and "flops" in r and "roofline_frac" in r, r
    assert any(r["hbm_bytes"] > 0 for r in records)
    led = core.runner.cost_registry.ledger()
    assert "decode" in led and led["decode"]["bytes"] > 0
    assert led["decode"]["bound"] in ("memory", "compute")


async def test_cost_counters_monotone_across_scrapes():
    from dynamo_tpu.observability.metrics import EngineMetrics
    from dynamo_tpu.top import parse_prometheus

    core = _run_mock_core()
    metrics = EngineMetrics(worker="w1").bind_core(core)

    def counter_value(text, name, kind):
        total = 0.0
        found = False
        for n, lab, v in parse_prometheus(text):
            if n == name and lab.get("step_kind") == kind:
                total, found = total + v, True
        assert found, f"{name} missing from scrape"
        return total

    text1 = (await metrics.render()).decode()
    first = counter_value(text1, "dynamo_engine_hbm_bytes_total", "decode")
    assert first > 0
    assert counter_value(text1, "dynamo_engine_flops_total", "decode") > 0
    # Second scrape with no new work: delta-sync must not double-count.
    text2 = (await metrics.render()).decode()
    assert counter_value(text2, "dynamo_engine_hbm_bytes_total", "decode") == first
    # More work strictly raises the counter.
    core.add_request(_greedy_req([5, 6, 7], max_tokens=3))
    for _ in range(32):
        if not core.has_work:
            break
        core.step()
    text3 = (await metrics.render()).decode()
    assert counter_value(text3, "dynamo_engine_hbm_bytes_total", "decode") > first
    # Gauges: one roofline sample per (step_kind, bound).
    assert any(
        n == "dynamo_engine_roofline_frac" and lab.get("step_kind") == "decode"
        for n, lab, _ in parse_prometheus(text3)
    )


# -- HTTP surfaces ------------------------------------------------------------


async def test_worker_debug_server_serves_cost():
    from dynamo_tpu.observability.http import WorkerDebugServer
    from dynamo_tpu.observability.metrics import EngineMetrics

    reg = CostRegistry(worker="w-0", peaks=(100.0, 1.0))
    reg.submit("step", (1,), "decode", estimate={"bytes": 64.0, "flops": 8.0})
    reg.observe("step", (1,), 0.001, "decode")
    server = WorkerDebugServer(EngineMetrics(worker="w-0"), cost=reg)
    port = await server.start("127.0.0.1", 0)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/debug/cost") as r:
                assert r.status == 200
                doc = await r.json()
        assert doc["enabled"] is True
        assert doc["peaks"]["source"] == "caller"
        assert doc["programs"][0]["program"] == "step"
        assert doc["ledger"]["decode"]["bytes"] == 64.0
    finally:
        await server.close()
    # Cost plane off: 200 with enabled=false, not a 404.
    server = WorkerDebugServer(EngineMetrics(worker="w-0"), cost=None)
    port = await server.start("127.0.0.1", 0)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/debug/cost") as r:
                assert r.status == 200
                assert (await r.json())["enabled"] is False
    finally:
        await server.close()


class _FakeCostTelemetry:
    """WorkerTelemetryClient stand-in for the frontend fan-out routes."""

    def __init__(self, capture_doc):
        self.capture_doc = capture_doc
        self.capture_calls = []

    async def collect_cost(self):
        return {"w-1": {"enabled": True, "ledger": {"decode": {"bytes": 10.0}}},
                "w-2": {"enabled": False}}

    async def profile_status(self, worker=None):
        docs = {"w-1": {"available": True, "running": False},
                "w-2": {"available": False, "running": False}}
        if worker in (None, "all"):
            return docs
        return {k: v for k, v in docs.items() if k == worker}

    async def capture_profile(self, worker, duration_ms):
        self.capture_calls.append((worker, duration_ms))
        if worker == "w-missing":
            return None
        return dict(self.capture_doc)

    async def collect_metrics_texts(self):
        return []


async def _cost_frontend(capture_doc):
    from dynamo_tpu.frontend.http import HttpService
    from dynamo_tpu.frontend.metrics import FrontendMetrics
    from dynamo_tpu.frontend.model_manager import ModelManager

    telemetry = _FakeCostTelemetry(capture_doc)
    service = HttpService(ModelManager(), metrics=FrontendMetrics(), telemetry=telemetry)
    port = await service.start("127.0.0.1", 0)
    return service, f"http://127.0.0.1:{port}", telemetry


async def test_frontend_debug_cost_and_profile_routes():
    ok_doc = {"ok": True, "artifact": "/tmp/p/w-1-1", "file_count": 2,
              "files": ["a.pb", "b.json"], "total_bytes": 10, "duration_ms": 50.0}
    service, base, telemetry = await _cost_frontend(ok_doc)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/cost") as r:
                assert r.status == 200
                doc = await r.json()
            assert doc["count"] == 2
            assert doc["workers"]["w-1"]["ledger"]["decode"]["bytes"] == 10.0
            assert doc["workers"]["w-2"]["enabled"] is False

            async with s.get(f"{base}/debug/profile/w-1") as r:
                assert r.status == 200
                assert (await r.json())["workers"]["w-1"]["available"] is True
            async with s.get(f"{base}/debug/profile/w-nope") as r:
                assert r.status == 404

            async with s.post(f"{base}/debug/profile/w-1?duration_ms=50") as r:
                assert r.status == 200
                cap = await r.json()
            assert cap["ok"] and cap["artifact"] == "/tmp/p/w-1-1"
            assert telemetry.capture_calls == [("w-1", 50.0)]
            async with s.post(f"{base}/debug/profile/w-missing") as r:
                assert r.status == 404
            async with s.post(f"{base}/debug/profile/w-1?duration_ms=banana") as r:
                assert r.status == 400
    finally:
        await service.stop()


async def test_frontend_profile_refusals_map_to_http_statuses():
    for reason, status in (("busy", 409), ("profiler_unavailable", 501),
                           ("capture_failed", 502)):
        service, base, _ = await _cost_frontend({"ok": False, "reason": reason})
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/debug/profile/w-1") as r:
                    assert r.status == status, reason
                    assert (await r.json())["reason"] == reason
        finally:
            await service.stop()


# -- profile capture service --------------------------------------------------


async def _one(agen):
    return [doc async for doc in agen][0]


async def test_profile_service_status_and_unavailable(monkeypatch, tmp_path):
    from dynamo_tpu.observability.service import ProfileCaptureService

    monkeypatch.setenv("DYN_PROFILE_DIR", str(tmp_path))
    svc = ProfileCaptureService(worker="w-7")
    status = await _one(svc.generate({}, Context()))
    assert status["worker"] == "w-7"
    assert status["artifact_dir"] == str(tmp_path)
    assert "available" in status and "running" in status

    # A stripped build (no jax.profiler): structured refusal, not an error.
    monkeypatch.setattr(cost_mod, "profiler_available", lambda: False)
    doc = await _one(svc.generate({"action": "capture"}, Context()))
    assert doc["ok"] is False and doc["reason"] == "profiler_unavailable"


async def test_profile_service_capture_and_single_flight(monkeypatch, tmp_path):
    import dynamo_tpu.tracing as tracing
    from dynamo_tpu.observability.service import ProfileCaptureService

    monkeypatch.setenv("DYN_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("DYN_PROFILE_MAX_MS", "100")
    monkeypatch.setattr(cost_mod, "profiler_available", lambda: True)

    async def fake_profile_for(seconds, log_dir):
        # Clamp applied upstream: 5000 ms request, 100 ms cap.
        assert seconds == pytest.approx(0.1)
        os.makedirs(log_dir, exist_ok=True)
        with open(os.path.join(log_dir, "t.xplane.pb"), "wb") as f:
            f.write(b"x" * 16)
        return log_dir

    monkeypatch.setattr(tracing, "profile_for", fake_profile_for)
    svc = ProfileCaptureService(worker="w-7")
    doc = await _one(svc.generate({"action": "capture", "duration_ms": 5000}, Context()))
    assert doc["ok"] is True
    assert doc["file_count"] == 1 and doc["files"] == ["t.xplane.pb"]
    assert doc["total_bytes"] == 16
    assert doc["artifact"].startswith(str(tmp_path))

    # Single-flight: profile_for answers None when a trace is running.
    async def busy_profile_for(seconds, log_dir):
        return None

    monkeypatch.setattr(tracing, "profile_for", busy_profile_for)
    doc = await _one(svc.generate({"action": "capture"}, Context()))
    assert doc["ok"] is False and doc["reason"] == "busy"


def test_device_trace_single_flight_primitive(tmp_path):
    """tracing.start_device_trace's single-flight lock, which the capture
    service inherits: a second arm while one runs is refused."""
    from dynamo_tpu import tracing

    if not cost_mod.profiler_available():
        pytest.skip("jax.profiler unavailable")
    assert tracing.start_device_trace(str(tmp_path / "t")) is True
    try:
        assert tracing.trace_running() is True
        assert tracing.start_device_trace(str(tmp_path / "t2")) is False
    finally:
        assert tracing.stop_device_trace() == str(tmp_path / "t")
    assert tracing.trace_running() is False


# -- control tower + incident bundle ------------------------------------------


def test_top_renders_roofline_panel():
    from dynamo_tpu.top import FleetSnapshot, render

    samples = [
        ("dynamo_engine_roofline_frac",
         {"worker": "w-1", "step_kind": "decode", "bound": "memory"}, 0.72),
        ("dynamo_engine_roofline_frac",
         {"worker": "w-1", "step_kind": "prefill", "bound": "compute"}, 0.31),
    ]
    frame = render(FleetSnapshot(samples, None, None, []), url="http://x")
    assert "roofline" in frame
    assert "decode" in frame and "memory-bound" in frame
    assert "0.720" in frame and "compute-bound" in frame
    # No samples: the panel says why instead of vanishing.
    empty = render(FleetSnapshot([], None, None, []), url="http://x")
    assert "no cost-plane samples" in empty


def test_incident_bundle_embeds_cost_and_capture_state(tmp_path, monkeypatch):
    from dynamo_tpu.observability.incidents import IncidentCapture, IncidentStore

    monkeypatch.setenv("DYN_PROFILE_DIR", str(tmp_path / "profiles"))
    core = _run_mock_core()
    recorder = IncidentCapture(
        store=IncidentStore(str(tmp_path / "inc")), core=core, worker="w-1"
    )
    bundle_id = recorder.capture("anomaly", {"detector": "step_gap_regression"})
    bundle = recorder.store.get(bundle_id)
    assert bundle["cost"]["enabled"] is True
    assert bundle["cost"]["ledger"]["decode"]["bytes"] > 0
    trace_state = bundle["device_trace"]
    assert "capture_available" in trace_state
    assert trace_state["artifact_dir"] == str(tmp_path / "profiles")


# -- DYN_COST_PLANE=0 acceptance ---------------------------------------------


def _tiny_core_tokens():
    from dynamo_tpu.engine.core import EngineConfig, EngineCore
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import PRESETS

    cfg = PRESETS["test-tiny"]
    params = llama.init_params(cfg, 0)
    runner = ModelRunner(cfg, params, num_pages=64, page_size=4, max_batch_size=8,
                         prefill_bucket=16, attn_impl="reference")
    core = EngineCore(runner, EngineConfig(
        num_pages=64, page_size=4, max_batch_size=8, max_prefill_tokens=256,
        max_seq_len=64, decode_steps=2,
    ))
    rng = np.random.default_rng(0)
    core.add_request(_greedy_req(
        rng.integers(1, cfg.vocab_size - 1, size=8).tolist(), max_tokens=6))
    tokens = []
    for _ in range(64):
        if not core.has_work:
            break
        for _, out in core.step():
            tokens.extend(out.token_ids)
    return runner, tokens


def test_cost_plane_off_bit_identical_zero_extractions(monkeypatch):
    """The hard gate: DYN_COST_PLANE=0 must produce the same tokens with no
    registry and no extraction lowerings at all (EXTRACTIONS spy flat)."""
    monkeypatch.setenv("DYN_COST_PLANE", "1")
    assert cost_plane_enabled()
    runner_on, tokens_on = _tiny_core_tokens()
    assert runner_on.cost_registry is not None
    assert runner_on.cost_registry.drain(timeout=60.0)
    assert runner_on.cost_registry.extract_calls > 0
    led = runner_on.cost_registry.ledger()
    assert "decode" in led and led["decode"]["bytes"] > 0

    monkeypatch.setenv("DYN_COST_PLANE", "0")
    assert not cost_plane_enabled()
    before = cost_mod.EXTRACTIONS
    runner_off, tokens_off = _tiny_core_tokens()
    assert runner_off.cost_registry is None
    assert cost_mod.EXTRACTIONS == before, "extraction ran with the plane off"
    assert tokens_on == tokens_off and len(tokens_on) == 6
