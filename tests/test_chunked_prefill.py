"""Golden parity of chunked prefill vs whole-prompt prefill (ISSUE 2).

The mixed-step scheduler splits prompts into token-budget chunks; sampling
is suppressed for non-final chunks and the rng fold counter does not
advance on suppression, so the final chunk must sample exactly what a
whole-prompt prefill samples — tokens AND logprobs, greedy and seeded —
across chunk-boundary sizes, with prefix-cache resumes, preemption
mid-prompt, and multimodal rows (mm_slot_offset advancing across chunks).
"""

import numpy as np
import pytest

from dynamo_tpu.engine.core import EngineConfig, EngineCore
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import PRESETS
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

from tests.test_engine_core import greedy_reference, greedy_request, run_to_completion

CFG = PRESETS["test-tiny"]
PARAMS = llama.init_params(CFG, 0)
PAGE = 4


def make_core(chunk=4, num_pages=64, max_batch=8, max_prefill=256, **cfg_kw):
    config = EngineConfig(
        num_pages=num_pages, page_size=PAGE, max_batch_size=max_batch,
        max_prefill_tokens=max_prefill, max_seq_len=128,
        chunk_prefill_tokens=chunk, **cfg_kw,
    )
    runner = ModelRunner(
        CFG, PARAMS, num_pages=num_pages, page_size=PAGE,
        max_batch_size=max_batch, prefill_bucket=16, attn_impl="reference",
    )
    return EngineCore(runner, config)


@pytest.mark.parametrize("chunk", [3, 4, 5, 8, 11])
def test_chunked_equals_whole_prompt_across_chunk_sizes(chunk):
    """Chunk boundaries off/on page boundaries, mid-prompt and at the final
    token: every size must reproduce the whole-prompt greedy tokens.
    max_prefill_tokens == chunk forces chunking even with no decode rows."""
    prompt = [5, 6, 7, 8, 9, 10, 11, 3, 1, 4, 1, 5, 9]  # 13 tokens
    core = make_core(chunk=chunk, max_prefill=chunk)
    seq = core.add_request(greedy_request(prompt, max_tokens=6))
    outputs = run_to_completion(core)
    assert outputs[seq.seq_id] == greedy_reference(prompt, 6)
    assert seq.prefill_chunks >= -(-len(prompt) // chunk) - 1


def test_mixed_step_parity_with_running_decode():
    """Prompts admitted while decodes run are chunked at the budget and ride
    fused mixed steps; everyone stays token-exact, and no prefill-only step
    ever starves the running decodes."""
    core = make_core(chunk=4)
    p1 = [1, 2, 3, 4, 5]
    core.add_request(greedy_request(p1, max_tokens=16))
    outputs = {}
    for _ in range(3):  # prefill p1 + a couple of decode steps
        for seq, out in core.step():
            outputs.setdefault(seq.seq_id, []).extend(out.token_ids)
    p2 = list(range(7, 7 + 17))  # 17 tokens: 5 chunks of <=4
    p3 = [9, 8, 7, 6, 5, 4, 3]
    core.add_request(greedy_request(p2, max_tokens=5))
    core.add_request(greedy_request(p3, max_tokens=5))
    outputs = run_to_completion(core, outputs=outputs)
    assert outputs[0] == greedy_reference(p1, 16)
    assert outputs[1] == greedy_reference(p2, 5)
    assert outputs[2] == greedy_reference(p3, 5)
    assert core.mixed_steps > 0
    assert core.stall_violations == 0


def test_seeded_sampling_parity_chunked_vs_whole():
    """The rng fold counter must not advance on suppressed (non-final-chunk)
    samples: a seeded request generates the identical stream either way."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]

    def run(chunk, max_prefill):
        core = make_core(chunk=chunk, max_prefill=max_prefill)
        req = PreprocessedRequest(
            token_ids=list(prompt),
            sampling=SamplingOptions(temperature=0.9, top_k=40, top_p=0.95, seed=1234),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
        )
        seq = core.add_request(req)
        run_to_completion(core)
        return seq.tokens[len(prompt):]

    whole = run(chunk=0, max_prefill=256)
    for chunk in (3, 4, 7):
        assert run(chunk=chunk, max_prefill=chunk) == whole, f"chunk={chunk}"


def test_logprob_parity_chunked_vs_whole():
    """Reported logprobs (chosen + top-k) of the final-chunk sample and all
    decode steps match the whole-prompt run."""
    prompt = [3, 5, 7, 11, 13, 2, 4, 6, 8, 10]

    def run(chunk, max_prefill):
        core = make_core(chunk=chunk, max_prefill=max_prefill)
        core.add_request(PreprocessedRequest(
            token_ids=list(prompt),
            sampling=SamplingOptions(temperature=0.0, logprobs=4),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
        ))
        toks, lps = [], []
        while core.has_work:
            for _seq, out in core.step():
                toks.extend(out.token_ids)
                if out.logprobs:
                    lps.extend(out.logprobs)
        return toks, lps

    toks_w, lps_w = run(chunk=0, max_prefill=256)
    toks_c, lps_c = run(chunk=4, max_prefill=4)
    assert toks_c == toks_w
    assert len(lps_c) == len(lps_w) == 4
    for ec, ew in zip(lps_c, lps_w):
        assert ec["id"] == ew["id"]
        np.testing.assert_allclose(ec["logprob"], ew["logprob"], rtol=1e-4, atol=1e-5)
        assert [tid for tid, _ in ec["top"]] == [tid for tid, _ in ew["top"]]
        np.testing.assert_allclose(
            [lp for _, lp in ec["top"]], [lp for _, lp in ew["top"]],
            rtol=1e-4, atol=1e-5,
        )


def test_prefix_cache_hit_then_chunked_resume():
    """A second request over a cached prefix starts its first chunk at the
    matched boundary (num_cached > 0) and continues chunked to parity."""
    prompt = list(range(1, 21))  # 20 tokens = 5 full pages
    core = make_core(chunk=4, max_prefill=4)
    core.add_request(greedy_request(prompt, max_tokens=2))
    run_to_completion(core)
    seq = core.add_request(greedy_request(prompt, max_tokens=3))
    outputs = run_to_completion(core)
    assert seq.num_cached_at_start >= PAGE  # hit at least one cached page
    assert seq.num_cached_at_start < len(prompt)  # but still had chunks to run
    assert outputs[seq.seq_id] == greedy_reference(prompt, 3)


def test_preemption_then_chunked_reprefill():
    """Page pressure preempts a sequence mid-stream; its resume (prompt +
    generated recompute) runs as budget chunks interleaved with the
    survivor's decode, and both streams stay token-exact."""
    core = make_core(chunk=4, num_pages=8, max_batch=2, enable_prefix_caching=False)
    p1, p2 = [1, 2, 3, 4, 5, 6], [11, 12, 13, 14]
    core.add_request(greedy_request(p1, max_tokens=10))
    core.add_request(greedy_request(p2, max_tokens=10))
    outputs = run_to_completion(core, max_steps=400)
    assert core.num_preemptions > 0, "test must exercise the preemption path"
    assert outputs[0] == greedy_reference(p1, 10)
    assert outputs[1] == greedy_reference(p2, 10)


def test_chunked_decode_steps_pipeline_interleave():
    """Chunked admission composes with the fused-burst decode path: bursts
    drain when chunks arrive, then resume; tokens stay exact."""
    core = make_core(chunk=4, decode_steps=4)
    p1 = [1, 2, 3, 4, 5]
    core.add_request(greedy_request(p1, max_tokens=12))
    outputs = {}
    for _ in range(3):
        for seq, out in core.step():
            outputs.setdefault(seq.seq_id, []).extend(out.token_ids)
    p2 = list(range(7, 7 + 13))
    core.add_request(greedy_request(p2, max_tokens=6))
    outputs = run_to_completion(core, outputs=outputs)
    assert outputs[0] == greedy_reference(p1, 12)
    assert outputs[1] == greedy_reference(p2, 6)


# -- multimodal: mm_slot_offset advancing across chunks ----------------------

VL_CFG = PRESETS["test-tiny-vl"]
IMG = VL_CFG.image_token_id


def _mm_payload(embeds: np.ndarray) -> dict:
    import base64

    return {
        "embeds_b64": base64.b64encode(
            np.ascontiguousarray(embeds, np.float32).tobytes()).decode(),
        "shape": list(embeds.shape),
        "dtype": "float32",
    }


def _vl_core(params, chunk, max_prefill=256):
    runner = ModelRunner(VL_CFG, params, num_pages=64, page_size=PAGE,
                         max_batch_size=4, prefill_bucket=16)
    return EngineCore(runner, EngineConfig(
        num_pages=64, page_size=PAGE, max_batch_size=4,
        max_prefill_tokens=max_prefill, max_seq_len=128,
        enable_prefix_caching=False, chunk_prefill_tokens=chunk,
    ))


def _vl_run(core, token_ids, mm, max_tokens=6):
    seq = core.add_request(PreprocessedRequest(
        token_ids=list(token_ids),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        mm_inputs=_mm_payload(mm),
    ))
    while not seq.is_finished:
        core.step()
    return seq.tokens[len(token_ids):]


@pytest.mark.parametrize("chunk", [3, 4, 6])
def test_multimodal_chunked_equals_whole(chunk):
    """Placeholders split across chunk boundaries: each chunk row's
    mm_slot_offset counts the placeholders already covered by earlier
    chunks, so later chunks inject the correct embedding rows. Chunked
    output must equal the whole-prompt run."""
    rng = np.random.default_rng(7)
    params = llama.init_params(VL_CFG, 0)
    # Placeholders land in different chunks for every parametrized size.
    prompt = [5, 6, IMG, IMG, 9, 10, 11, 12, 20, 21, 22, 23, 24, IMG, IMG, 25]
    mm = rng.standard_normal((4, VL_CFG.hidden_size)).astype(np.float32)

    whole = _vl_run(_vl_core(params, chunk=0), prompt, mm)
    chunked = _vl_run(_vl_core(params, chunk=chunk, max_prefill=chunk), prompt, mm)
    assert chunked == whole


def test_multimodal_chunk_rides_mixed_step_with_decode():
    """A multimodal prompt chunked while a text sequence decodes: the decode
    row keeps offset -1 (no substitution), the chunk rows advance theirs."""
    rng = np.random.default_rng(11)
    params = llama.init_params(VL_CFG, 0)
    prompt_mm = [5, 6, IMG, IMG, 9, 10, 11, 12, 20, 21, IMG, 22]
    mm = rng.standard_normal((3, VL_CFG.hidden_size)).astype(np.float32)

    whole = _vl_run(_vl_core(params, chunk=0), prompt_mm, mm)

    core = _vl_core(params, chunk=4)
    text = core.add_request(greedy_request([7, 8, 9, 10], max_tokens=14))
    for _ in range(3):
        core.step()
    text_solo_ref = list(text.tokens[4:])
    out_mm = _vl_run(core, prompt_mm, mm)
    while not text.is_finished:
        core.step()
    assert out_mm == whole
    assert core.mixed_steps > 0

    # The text neighbor is unaffected by sharing steps with the mm chunks.
    solo = _vl_core(params, chunk=4)
    ref = solo.add_request(greedy_request([7, 8, 9, 10], max_tokens=14))
    while not ref.is_finished:
        solo.step()
    assert text.tokens[4:] == ref.tokens[4:]
    assert text.tokens[4 : 4 + len(text_solo_ref)] == text_solo_ref
