"""KV router unit tests: indexer overlap walking, scheduler cost/softmax,
event subscription plumbing, recorder replay."""

import numpy as np

from dynamo_tpu.protocols.kv import BlockRemoved, BlockStored, ForwardPassMetrics, KvCacheEvent, RouterEvent
from dynamo_tpu.router.indexer import KvIndexer
from dynamo_tpu.router.recorder import KvRecorder, replay
from dynamo_tpu.router.scheduler import KvScheduler, SchedulerConfig
from dynamo_tpu.tokens import compute_block_hashes


def stored(wid, *hashes, parents=None):
    parents = parents or [None] * len(hashes)
    return RouterEvent(wid, KvCacheEvent(stored=[BlockStored(h, p) for h, p in zip(hashes, parents)]))


def removed(wid, *hashes):
    return RouterEvent(wid, KvCacheEvent(removed=[BlockRemoved(h) for h in hashes]))


# -- indexer -----------------------------------------------------------------


def test_find_matches_consecutive_prefix():
    idx = KvIndexer()
    idx.apply_event(stored(1, 10, 11, 12))
    idx.apply_event(stored(2, 10, 11))
    idx.apply_event(stored(3, 99))
    scores = idx.find_matches([10, 11, 12, 13]).scores
    assert scores == {1: 3, 2: 2}
    assert idx.find_matches([99]).scores == {3: 1}
    assert idx.find_matches([13, 10]).scores == {}  # must match from the start


def test_removed_blocks_stop_matching():
    idx = KvIndexer()
    idx.apply_event(stored(1, 10, 11))
    idx.apply_event(removed(1, 11))
    assert idx.find_matches([10, 11]).scores == {1: 1}


def test_remove_worker_and_cleared():
    idx = KvIndexer()
    idx.apply_event(stored(1, 10, 11))
    idx.apply_event(stored(2, 10))
    idx.remove_worker(1)
    assert idx.find_matches([10, 11]).scores == {2: 1}
    idx.apply_event(RouterEvent(2, KvCacheEvent(cleared=True)))
    assert idx.find_matches([10]).scores == {}
    assert idx.num_blocks == 0


def test_indexer_matches_engine_hashes():
    # The indexer must agree with the engine's chained block hashing.
    tokens = list(range(32))
    hashes = compute_block_hashes(tokens, 8)
    idx = KvIndexer()
    parents = [None] + hashes[:-1]
    idx.apply_event(stored(7, *hashes, parents=parents))
    assert idx.find_matches(compute_block_hashes(tokens, 8)).scores == {7: 4}
    # A different continuation shares only the common prefix.
    other = compute_block_hashes(tokens[:16] + [999] * 16, 8)
    assert idx.find_matches(other).scores == {7: 2}


# -- scheduler ---------------------------------------------------------------


def make_metrics(wid, usage=0.0, waiting=0, slots=10):
    return ForwardPassMetrics(
        worker_id=wid, kv_active_blocks=int(usage * 100), kv_total_blocks=100,
        num_requests_waiting=waiting, request_total_slots=slots,
    )


def test_scheduler_prefers_overlap():
    s = KvScheduler(SchedulerConfig(overlap_weight=1.0, temperature=0.0))
    from dynamo_tpu.router.indexer import OverlapScores

    overlaps = OverlapScores({1: 8, 2: 0})
    metrics = {1: make_metrics(1), 2: make_metrics(2)}
    assert s.schedule(10, overlaps, metrics, [1, 2]) == 1


def test_scheduler_load_beats_small_overlap():
    s = KvScheduler(SchedulerConfig(overlap_weight=1.0, temperature=0.0))
    from dynamo_tpu.router.indexer import OverlapScores

    # Worker 1 has 1 block overlap but is saturated; worker 2 is idle.
    overlaps = OverlapScores({1: 1})
    metrics = {1: make_metrics(1, usage=0.95, waiting=9), 2: make_metrics(2)}
    assert s.schedule(10, overlaps, metrics, [1, 2]) == 2


def test_scheduler_softmax_spreads_ties():
    s = KvScheduler(SchedulerConfig(temperature=0.5, seed=0))
    from dynamo_tpu.router.indexer import OverlapScores

    picks = {s.schedule(4, OverlapScores({}), {}, [1, 2, 3]) for _ in range(50)}
    assert len(picks) > 1  # samples, not always the same worker


def test_scheduler_deterministic_tiebreak():
    s = KvScheduler(SchedulerConfig(temperature=0.0))
    from dynamo_tpu.router.indexer import OverlapScores

    assert s.schedule(4, OverlapScores({}), {}, [5, 3, 9]) == 3


# -- recorder ----------------------------------------------------------------


def test_recorder_roundtrip(tmp_path):
    path = tmp_path / "events.jsonl"
    ev = stored(1, 10, 11)
    with KvRecorder(path) as rec:
        rec.record(ev)
        rec.record(removed(1, 10))
    events = list(replay(path))
    assert len(events) == 2
    idx = KvIndexer()
    for _, e in events:
        idx.apply_event(e)
    assert idx.find_matches([10, 11]).scores == {}  # 10 removed breaks the chain at the start
    assert idx.find_matches([11]).scores == {1: 1}  # 11 itself is still held
    assert idx.worker_block_counts() == {1: 1}


# -- snapshot / late join ----------------------------------------------------


def test_allocator_snapshot_orders_parents_first():
    from dynamo_tpu.engine.allocator import PageAllocator

    alloc = PageAllocator(num_pages=8, page_size=4)
    a, b, c = alloc.allocate(3)
    alloc.commit(a, 100, None)
    alloc.commit(b, 200, 100)
    alloc.commit(c, 300, 200)
    snap = alloc.cache_snapshot()
    hashes = [s.block_hash for s in snap.stored]
    assert hashes.index(100) < hashes.index(200) < hashes.index(300)
    # Applying the snapshot to a fresh indexer reconstructs the chain.
    idx = KvIndexer()
    idx.apply_event(RouterEvent(5, snap))
    assert idx.find_matches([100, 200, 300]).scores == {5: 3}


async def test_broadcaster_snapshot_for_late_subscriber():
    from dynamo_tpu.protocols.kv import BlockStored
    from dynamo_tpu.router.events import KvEventBroadcaster
    from dynamo_tpu.runtime.engine import Context

    snap_event = KvCacheEvent(stored=[BlockStored(42, None)])
    bc = KvEventBroadcaster(snapshot_fn=lambda: snap_event)
    bc.publish(KvCacheEvent(stored=[BlockStored(1, None)]))  # before subscribe
    ctx = Context()
    stream = bc.generate({}, ctx)
    first = await stream.__anext__()
    assert first["snapshot"] is True and first["seq"] == 1
    assert first["event"]["stored"][0]["block_hash"] == 42
    bc.publish(KvCacheEvent(stored=[BlockStored(2, None)]))
    second = await stream.__anext__()
    assert second["seq"] == 1 and not second.get("snapshot")
    ctx.stop_generating()
    await stream.aclose()
